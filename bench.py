#!/usr/bin/env python
"""Synthetic image-model benchmark — the rebuild's analog of reference
``examples/tensorflow2_synthetic_benchmark.py`` (ResNet-50, synthetic images,
img/s). ``--model`` also covers the reference scaling table's resnet101 /
inception3 / vgg16 (``docs/benchmarks.rst:10-14``). Prints ONE JSON line:

    {"metric": "resnet50_images_per_sec_per_chip", "value": ..., "unit":
     "img/s/chip", "vs_baseline": ...}

Baseline: the reference's only published absolute number, 103.6 img/s/GPU
(tf_cnn_benchmarks ResNet-101, bs 64/GPU, 16 Pascal P100 over 25GbE —
``docs/benchmarks.rst:26-42``; see BASELINE.md).

Default mode is an escalation ladder over the whole ``--run-timeout``
budget: probe the backend on an interval until a healthy window appears,
then climb headline-first (bf16-matmul MFU sanity probe → the img/s
workload with essentially all remaining time → TransformerLM →
control-plane e2e → XLA device trace → Pallas flash attention), each in a
watchdogged child, merging completed rungs — and anything the round-long
``tools/tpu_window_watcher.py`` captured earlier — into the final JSON
line as auxiliary fields. ``--no-probe`` runs just the watchdogged img/s
child (the watcher's rung / CI mode).
"""

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S_PER_CHIP = 103.6



# name -> (models attr, default image size, has reference baseline).
# resnet101/inception3/vgg16 are the reference's scaling-table workloads
# (docs/benchmarks.rst:10-14); its only *absolute* number is the ResNet-type
# 103.6 img/s/GPU, so vs_baseline is null for the other families.
_MODELS = {
    "resnet50": ("ResNet50", 224, True),
    "resnet101": ("ResNet101", 224, True),
    "inception3": ("InceptionV3", 299, False),
    "vgg16": ("VGG16", 224, False),
}


def _emit_skip(reason: str, model: str = "resnet50") -> None:
    print(
        json.dumps(
            {
                "metric": f"{model}_images_per_sec_per_chip",
                "value": None,
                "unit": "img/s/chip",
                "vs_baseline": None,
                "skipped": reason,
            }
        ),
        flush=True,
    )


def _watcher():
    """Import the window-watcher module (probe / run_rung / TRACE_CODE) with
    its log stream pointed at stderr, keeping this process's stdout a single
    parseable JSON line."""
    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    import tpu_window_watcher as w

    w.LOG_STREAM = "stderr"  # late-bound: always the CURRENT sys.stderr
    return w


def _best_artifacts(art_dir: str, model: str,
                    max_age_hours: float = None) -> dict:
    """Scan the round-long watcher's artifact dir for the best capture per
    rung. A number recorded at hour 2 of the round survives a chip that is
    wedged again when this script runs at hour 12 — the whole point of the
    watcher (VERDICT r4 item 1).

    Artifacts older than ``max_age_hours`` (default: the watcher's shared
    ``FRESHNESS_S``; file mtime) are ignored so a workspace reused across
    rounds never reports a previous round's numbers, and img/s artifacts
    are only merged when they benchmarked ``model``.
    """
    import statistics

    w = _watcher()
    max_age_s = (max_age_hours * 3600 if max_age_hours is not None
                 else w.FRESHNESS_S)
    best = {}
    ratios = []  # every fresh cpe2e capture (median, not best-of)
    for path, data in w.iter_fresh_artifacts(art_dir, max_age_s):
        rung = data.get("_rung")
        if rung is None or not w.artifact_ok(data):
            continue
        if (rung == "resnet"
                and data.get("metric") != f"{model}_images_per_sec_per_chip"):
            continue
        data["_path"] = path  # consumers (sync_evidence) copy the source
        cur = best.get(rung)
        if rung == "cpe2e":
            # a RATIO, not a throughput: "max across captures" selected the
            # luckiest window's noise — the median over all fresh captures
            # (with the count alongside) is the honest central estimate
            ratios.append(data)
        elif rung in ("mfu", "resnet", "lm"):
            # throughput rungs: keep the max capture
            if cur is None or data["value"] > cur["value"]:
                best[rung] = data
        else:  # flash / trace: latest capture wins (paths sort by timestamp)
            best[rung] = data
    if ratios:
        med = statistics.median(d["value"] for d in ratios)
        # report the capture whose value IS (closest to) the median so its
        # provenance fields (_path, _captured_at, device) stay truthful
        rep = dict(min(ratios, key=lambda d: abs(d["value"] - med)))
        rep["value"] = med
        rep["captures"] = len(ratios)
        best["cpe2e"] = rep
    return best


def _art_dir(args) -> str:
    """The watcher artifact dir: --artifacts, else .tpu_watch next to this
    script (one resolution for the ladder, the child env, and the merge)."""
    return getattr(args, "artifacts", None) or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".tpu_watch")


def _emit_merged(args, best: dict, reason) -> None:
    """ONE JSON line: the img/s rung as the primary metric when any run or
    artifact captured it, with every other completed rung merged in as
    auxiliary fields — a partial ladder still records hardware numbers."""
    res = best.get("resnet")
    if res is not None:
        out = {k: v for k, v in res.items() if not k.startswith("_")}
        if res.get("_captured_at"):
            out["captured_at"] = res["_captured_at"]
    else:
        out = {
            "metric": f"{args.model}_images_per_sec_per_chip",
            "value": None,
            "unit": "img/s/chip",
            "vs_baseline": None,
            "skipped": reason or "img-per-sec-rung-not-captured",
        }
        # make the skip self-documenting: the round-long watcher's probe
        # statistics say how many healthy windows the round actually
        # offered (affirmative evidence, not log absence)
        try:
            path = os.path.join(_art_dir(args), "watch_summary.json")
            # same freshness policy as the rung artifacts: a summary left
            # over from a previous round must not claim ITS windows here
            if time.time() - os.path.getmtime(path) <= _watcher().FRESHNESS_S:
                with open(path) as f:
                    s = json.load(f)
                out["watcher_probes"] = s.get("probes")
                out["watcher_healthy_windows"] = s.get("healthy")
        except (OSError, ValueError):
            pass
    mfu = best.get("mfu")
    if mfu:
        out["bf16_matmul_tflops"] = mfu["value"]
        out["bf16_matmul_mfu"] = mfu.get("mfu_vs_peak")
        if mfu.get("hbm_gbps"):
            out["hbm_gbps"] = mfu["hbm_gbps"]
        out.setdefault("device_kind", mfu.get("device_kind"))
    lm = best.get("lm")
    if lm:
        out["transformer_lm_tokens_per_sec_per_chip"] = lm["value"]
        out["transformer_lm_mfu"] = lm.get("mfu")
    cpe2e = best.get("cpe2e")
    if cpe2e:
        out["control_plane_core_vs_injit_onchip"] = cpe2e["value"]
        if cpe2e.get("captures"):
            # median over this many fresh captures (not a best-of)
            out["control_plane_core_vs_injit_captures"] = cpe2e["captures"]
    flash = best.get("flash")
    if flash:
        out["flash_attention_onchip_ok"] = bool(flash.get("equivalent"))
        out["flash_attention_ms"] = flash.get("value")
        out["flash_speedup_vs_scan"] = flash.get("speedup_vs_scan")
    trace = best.get("trace")
    if trace:
        out["xla_trace_dir"] = trace.get("trace_dir")
    print(json.dumps(out), flush=True)


def _wait_for_watcher_rung(w, art: str, deadline: float) -> None:
    """If the background watcher is mid-rung (its ACTIVE lease names a live
    pid), wait for it to finish before probing — two backend inits against
    the tunnel at once is a known way to wedge the chip during the one
    driver window that matters. Bounded by the rung's own watchdog (<=960s)
    and by our deadline; a lease naming a dead pid is ignored."""
    active = w.rung_active_file(art)
    while time.time() < deadline - 120:
        try:
            with open(active) as f:
                parts = f.read().split()
            pid = int(parts[0]) if parts else 0
            # the lease records its own watchdog budget ("<pid> <timeout>",
            # run_rung); older than that + the two bounded 15 s reaps +
            # slack means a killed watcher left it behind, not a live rung.
            # A bare-pid lease (pre-upgrade watcher) falls back to the
            # longest rung budget of that era.
            lease_timeout = float(parts[1]) if len(parts) > 1 else 960.0
            if time.time() - os.path.getmtime(active) > lease_timeout + 140:
                w.log("ignoring stale watcher lease")
                return
            if pid <= 0:
                return  # partially-written lease; os.kill(0,0) would
                #         signal our own process group and always "succeed"
            os.kill(pid, 0)  # raises if the rung child is gone
        except (OSError, ValueError):
            return
        w.log(f"waiting for watcher rung (pid {pid}) to release the chip")
        time.sleep(15)


def _run_ladder(args) -> int:
    """Escalation ladder over the full --run-timeout budget (VERDICT r4
    item 1): re-probe on an interval until a healthy window appears, then
    climb headline-first — the bf16-matmul MFU sanity probe (<1 min), this
    script's own img/s workload with essentially all remaining time, then
    the auxiliary rungs (TransformerLM, control-plane e2e, XLA trace, Pallas
    flash) with whatever is left — each in a watchdogged child. Anything
    the round-long watcher already captured is merged in and not re-run."""
    w = _watcher()
    root = os.path.dirname(os.path.abspath(__file__))
    art = _art_dir(args)
    os.makedirs(art, exist_ok=True)
    pause = os.path.join(art, "PAUSE")
    with open(pause, "w"):
        pass  # signals the background watcher to stay off the chip
    try:
        deadline = time.time() + args.run_timeout
        _wait_for_watcher_rung(w, art, deadline)
        best = _best_artifacts(art, args.model)
        if best:
            w.log(f"bench: merged watcher artifacts for rungs {sorted(best)}")
        dev = None
        while time.time() < deadline - 60:
            dev = w.probe(45)
            if dev:
                break
            wait = min(args.probe_interval,
                       max(5, deadline - time.time() - 110))
            w.log(f"bench probe: wedged; retrying in {wait:.0f}s")
            time.sleep(wait)
        reason = None
        if dev is None:
            reason = "tpu-unavailable-all-probe-windows"
        else:
            w.log(f"bench probe healthy ({dev}); climbing ladder")
            py = sys.executable
            ladder = w.build_rungs(
                art, trace_dir=os.path.join(art, "xla_trace_bench"),
                include_resnet=False)
            # Headline first (round-5 lesson, same as the watcher's order):
            # the auxiliary rungs must never squeeze the img/s rung's budget.
            # mfu is the <1 min device sanity check; then the img/s child
            # gets essentially ALL remaining time; lm/cpe2e/trace/flash only
            # run with whatever the img/s rung left over (the round-long
            # watcher is their primary capture path anyway).
            window_open = True
            mfu_rungs = [r for r in ladder if r[0] == "mfu"]
            aux_rungs = [r for r in ladder if r[0] != "mfu"]
            for name, cmd, cap in mfu_rungs:
                if name in best:
                    continue  # watcher already captured it this round
                remaining = deadline - time.time()
                if remaining < 180:
                    break
                r = w.run_rung(name, cmd, int(min(cap, remaining - 120)), art)
                if r is not None:
                    best[name] = r
                elif w.reprobe_after_rung() is None:
                    w.log("window closed after mfu rung; not climbing")
                    window_open = False
            remaining = deadline - time.time()
            if window_open and "resnet" not in best and remaining > 150:
                cmd = [py, os.path.abspath(__file__),
                       "--model", args.model,
                       "--batch-size", str(args.batch_size),
                       "--warmup", str(args.warmup),
                       "--iters", str(args.iters),
                       "--image-size", str(args.image_size),
                       "--trace-dir",
                       args.trace_dir or os.path.join(art, "xla_trace_train"),
                       *(["--fp16-allreduce"] if args.fp16_allreduce else []),
                       "--in-process", "--no-probe"]
                r = w.run_rung("resnet", cmd, int(remaining - 90), art)
                if r is not None:
                    best["resnet"] = r
                elif w.reprobe_after_rung() is None:
                    window_open = False
            for name, cmd, cap in aux_rungs:
                if not window_open:
                    break
                if name in best:
                    continue
                remaining = deadline - time.time()
                if remaining < 150:
                    break
                r = w.run_rung(name, cmd, int(min(cap, remaining - 60)), art)
                if r is not None:
                    best[name] = r
                elif w.reprobe_after_rung() is None:
                    w.log("window closed mid-ladder; skipping pricier rungs")
                    break
            if not best:
                reason = "tpu-wedged-during-ladder"
        _emit_merged(args, best, reason)
    finally:
        try:
            os.unlink(pause)
        except OSError:
            pass
    return 0


def main():
    p = argparse.ArgumentParser()
    p.add_argument(
        "--model",
        choices=sorted(_MODELS),
        default="resnet50",
        help="benchmark workload; the reference's scaling table covers "
        "resnet101, inception3 and vgg16 (docs/benchmarks.rst:10-14)",
    )
    p.add_argument("--batch-size", type=int, default=128, help="per-chip batch")
    p.add_argument(
        "--image-size", type=int, default=None,
        help="default: 299 for inception3, else 224",
    )
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument(
        "--shard-optimizer", action="store_true",
        help="ZeRO-1: reduce-scatter gradient sync + sharded optimizer "
        "state (DistributedOptimizer(shard_optimizer=True))",
    )
    p.add_argument(
        "--zero-ab", action="store_true",
        help="run the sharded-vs-allreduce A/B rung (small explicit-"
        "collective model, both sync modes) and print its JSON line; "
        "records zero1_ab_* gauges + grad_sync_bytes_per_step in the "
        "metrics registry. CPU-safe.",
    )
    p.add_argument(
        "--fsdp-ab", action="store_true",
        help="run the ZeRO-3-vs-ZeRO-1 A/B rung (gather-on-use param "
        "sharding vs sharded optimizer state on the same small model); "
        "records fsdp_ab_step_ratio plus the measured "
        "param_gather_bytes_per_step / grad_sync_bytes_per_step gauges "
        "and prints ONE JSON line with the analytic zero3_sync_bytes "
        "model. CPU-safe; with no healthy device it still emits the "
        "byte-model line.",
    )
    p.add_argument(
        "--publish-ab", action="store_true",
        help="run the weight-publication A/B rung (same small model with "
        "streaming publication to an in-process KV on vs off) and print "
        "its JSON line; records publish_ab_step_ratio + "
        "serving_publish_wire_bytes gauges plus the analytic "
        "delta+int8-vs-full-checkpoint byte model. CPU-safe; with no "
        "healthy device it still emits the byte-model line.",
    )
    p.add_argument(
        "--serving-ab", action="store_true",
        help="run the serving-engine A/B rung: the same ragged request "
        "set decoded by the continuous-batching paged engine vs one "
        "static right-padded generate() batch; records "
        "serving_ab_goodput_ratio and prints ONE JSON line with the "
        "analytic slot-token goodput model "
        "(tools/scaling_projection.py::serving_goodput). CPU-safe; with "
        "no healthy device it still emits the model line.",
    )
    p.add_argument(
        "--prefix-ab", action="store_true",
        help="run the prefix-cache A/B rung: the same ragged request set "
        "served cold vs prefix-cached through one engine; records "
        "prefix_ab_prefill_ratio and prints ONE JSON line with the "
        "analytic prefill-token model "
        "(tools/scaling_projection.py::prefix_prefill_flops); the "
        "measured serving_prefill_tokens deltas must match the model "
        "exactly. CPU-safe; with no healthy device it still emits the "
        "model line.",
    )
    p.add_argument(
        "--spec-ab", action="store_true",
        help="run the speculative-decoding A/B rung: the same ragged "
        "request set decoded plain vs with a full-depth draft (100%% "
        "acceptance by construction); records spec_ab_goodput_ratio and "
        "prints ONE JSON line with the analytic acceptance model "
        "(tools/scaling_projection.py::spec_decode_tokens); the measured "
        "spec_proposed/spec_accepted counters must match the model "
        "exactly. CPU-safe; with no healthy device it still emits the "
        "model line.",
    )
    p.add_argument(
        "--straggler-ab", action="store_true",
        help="run the straggler A/B rung: the same eager-collective step "
        "loop with and without an injected HOROVOD_CHAOS rank_slow charge, "
        "with the fleet aggregator attributing the straggler live; "
        "records straggler_ab_step_ratio and prints ONE JSON line with "
        "the detected rank + measured arrival spread. CPU-safe.",
    )
    p.add_argument(
        "--numerics-ab", action="store_true",
        help="run the numerics-guard A/B rung: the same guarded train "
        "loop clean vs under a HOROVOD_CHAOS grad_spike charge; records "
        "the numerics_ab_step_ratio gauge (guarded-spiked / clean step "
        "time — the guard's overhead plus the skipped step) and prints "
        "ONE JSON line with the detection step. CPU-safe.",
    )
    p.add_argument(
        "--input-ab", action="store_true",
        help="run the input-pipeline A/B rung: the same jitted step fed "
        "by a ResumableLoader with prefetch on vs off (synchronous host "
        "gather); records the input_ab_step_ratio gauge (serial / "
        "overlapped step time) and prints ONE JSON line with the "
        "measured compute/load split plus the analytic "
        "tools/scaling_projection.py::input_step_time model. CPU-safe; "
        "with no healthy device it still emits the analytic-model line.",
    )
    p.add_argument(
        "--elastic-chaos", action="store_true",
        help="run the elastic chaos soak rung: inject rank_fail mid-run "
        "(HOROVOD_CHAOS), let the elastic coordinator shrink + regrow the "
        "mesh, and report the recovery latency as the "
        "elastic_recovery_latency_seconds gauge + one JSON line. CPU-safe.",
    )
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument(
        "--compression",
        choices=["none", "fp16", "int8", "powersgd"],
        default=None,
        help="gradient wire compression for the measured workload "
        "(HOROVOD_COMPRESSION spelling; powersgd implies error feedback "
        "and the ZeRO-1 exchange). --fp16-allreduce is the legacy alias "
        "for --compression fp16.",
    )
    p.add_argument(
        "--powersgd-rank", type=int, default=None,
        help="rank for --compression powersgd (default: "
        "HOROVOD_POWERSGD_RANK, else 4)",
    )
    p.add_argument(
        "--compression-ab", action="store_true",
        help="run the compression A/B rung (same small model through "
        "none/fp16/int8/powersgd sync) and print its JSON line; records "
        "compression_ab_step_ratio gauges + measured wire-byte gauges. "
        "CPU-safe; with no healthy device it still emits the byte-model "
        "A/B line so the perf trajectory is never empty.",
    )
    p.add_argument(
        "--overlap-ab", action="store_true",
        help="run the comm/compute-overlap A/B rung (same small model "
        "through the explicit-collective ZeRO-1 step, bucketed vs "
        "monolithic gradient sync) and print its JSON line; records the "
        "overlap_ab_step_ratio gauge + per-mode grad_sync_bytes_per_step "
        "and grad_sync_buckets, plus the analytic "
        "tools/scaling_projection.py::overlap_step_time model. CPU-safe; "
        "with no healthy device it still emits the analytic-model line.",
    )
    p.add_argument(
        "--pallas-ab", action="store_true",
        help="run the Pallas-kernel A/B rung (the same small ZeRO-1 + "
        "int8 + fused-Adam step with HOROVOD_PALLAS=1 vs =0) and print "
        "its JSON line; records the pallas_ab_step_ratio gauge, both "
        "arms' billed wire bytes vs the ring model, and the analytic "
        "tools/scaling_projection.py::pallas_hot_path_bytes HBM model "
        "(wire INVARIANCE itself is pinned by the schedule-fingerprint "
        "tests, not this gauge). CPU-safe: off-TPU the fused arm runs "
        "the kernels in Pallas interpret mode (an equivalence surface, "
        "so the CPU time ratio is interpreter overhead, not a speedup); "
        "with no healthy device it still emits the analytic-model line.",
    )
    p.add_argument(
        "--bucket-bytes", type=int, default=None,
        help="bucket capacity for --overlap-ab / overlapped workloads "
        "(default: HOROVOD_BUCKET_BYTES, else 256 KiB for the A/B's "
        "small model — the 64 MB production default would leave it one "
        "bucket and measure nothing)",
    )
    p.add_argument(
        "--no-probe",
        action="store_true",
        help="skip the probe loop + escalation ladder and just run the "
        "img/s workload in a watchdogged child (watcher rung / CI / CPU)",
    )
    p.add_argument(
        "--probe-interval",
        type=int,
        default=90,
        help="seconds between backend health probes while waiting for a "
        "healthy window (ladder mode)",
    )
    p.add_argument(
        "--artifacts",
        default=None,
        help="watcher artifact dir to merge + write (default: .tpu_watch "
        "next to this script)",
    )
    p.add_argument(
        "--run-timeout",
        type=int,
        default=1200,
        help="hard wall-clock cap (s) on the measured child run",
    )
    p.add_argument(
        "--trace-dir",
        default=None,
        help="after the timed loop, capture an XLA device trace of a few "
        "extra train steps into this dir (the real-workload overlap "
        "artifact; reference docs/timeline.rst analog)",
    )
    p.add_argument(
        "--in-process",
        action="store_true",
        help=argparse.SUPPRESS,  # child marker: run the workload here
    )
    args = p.parse_args()
    if args.iters < 1 or args.batch_size < 1:
        p.error("--iters and --batch-size must be >= 1")
    if args.image_size is None:
        args.image_size = _MODELS[args.model][1]

    if args.zero_ab:
        return _run_zero_ab(args)

    if args.fsdp_ab:
        return _run_fsdp_ab(args)

    if args.compression_ab:
        return _run_compression_ab(args)

    if args.overlap_ab:
        return _run_overlap_ab(args)

    if args.pallas_ab:
        return _run_pallas_ab(args)

    if args.publish_ab:
        return _run_publish_ab(args)

    if args.serving_ab:
        return _run_serving_ab(args)

    if args.prefix_ab:
        return _run_prefix_ab(args)

    if args.spec_ab:
        return _run_spec_ab(args)

    if args.straggler_ab:
        return _run_straggler_ab(args)

    if args.numerics_ab:
        return _run_numerics_ab(args)

    if args.input_ab:
        return _run_input_ab(args)

    if args.elastic_chaos:
        return _run_elastic_chaos(args)

    if args.in_process:
        return _run_benchmark(args)

    if not args.no_probe:
        # Default (driver) mode: probe-all-window escalation ladder, merging
        # anything the round-long watcher already captured (VERDICT r4 #1).
        return _run_ladder(args)

    # --no-probe: bare watchdogged-child mode.
    # The probe passing does NOT guarantee the run survives: the tunnel-TPU
    # in this environment has been observed to answer a probe and then wedge
    # inside the *next* process's backend init, blocked in an uninterruptible
    # C call — where an in-process SIGALRM handler never runs (the main
    # thread must re-enter the bytecode loop to deliver it; round-3 failure
    # mode). The only reliable watchdog is an external one: run the measured
    # workload in a child and enforce the timeout from here.
    # --in-process short-circuits before the probe, so the forwarded flags
    # (incl. --run-timeout) are inert in the child.
    cmd = [sys.executable, os.path.abspath(__file__), *sys.argv[1:],
           "--in-process", "--no-probe"]
    art = _art_dir(args)
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_watcher().jax_cache_env(art),
    )
    return _supervise_child(proc, args.run_timeout, args.model)


def _as_text(x):
    return x.decode("utf-8", "replace") if isinstance(x, bytes) else (x or "")


def _supervise_child(proc, run_timeout: int, model: str) -> int:
    """Reap the watchdogged measurement child and print ONE JSON line.

    On timeout, the child is killed but its flushed partial stdout is
    recovered (the child prints its headline line BEFORE the optional trace
    capture): a complete result line with a non-null value is printed with
    ``timed_out: true`` — the measurement finished, only the process did
    not. Partial output may ride the TimeoutExpired exception (bytes or str
    depending on the Python build) or only arrive from the bounded
    post-kill reap; the reap returns the FULL accumulated streams, so the
    exception's copies are the fallback. A child wedged in an
    uninterruptible device call can survive SIGKILL until the syscall
    returns - every reap is bounded."""
    try:
        stdout, stderr = proc.communicate(timeout=run_timeout)
    except subprocess.TimeoutExpired as e:
        proc.kill()
        stdout = _as_text(e.stdout)
        try:
            stdout2, stderr2 = proc.communicate(timeout=10)
            stdout = _as_text(stdout2) or stdout
            sys.stderr.write(_as_text(stderr2))
        except subprocess.TimeoutExpired:
            sys.stderr.write(_as_text(e.stderr))
        line = next(
            (ln for ln in reversed((stdout or "").splitlines())
             if ln.startswith("{")), None)
        data = None
        if line:
            try:
                data = json.loads(line)
            except ValueError:
                data = None
        if data is not None and data.get("value") is not None:
            data["timed_out"] = True  # measurement done; process was not
            print(json.dumps(data), flush=True)
        else:
            _emit_skip("tpu-wedged-during-run", model)
        return 0
    sys.stderr.write(stderr)
    result_line = next(
        (ln for ln in reversed(stdout.splitlines())
         if ln.startswith("{")), None
    )
    if proc.returncode != 0 or result_line is None:
        _emit_skip(f"benchmark-child-failed: rc={proc.returncode}", model)
        return 0
    print(result_line, flush=True)
    return 0


def _run_zero_ab(args):
    """Sharded-vs-allreduce A/B rung: train the same small MLP through the
    explicit-collective (shard_map) step twice — gradient allreduce vs the
    ZeRO-1 reduce-scatter/all-gather DistributedOptimizer — and record the
    step-time ratio plus both modes' ``grad_sync_bytes_per_step`` in the
    metrics registry. Prints ONE JSON line. Runs anywhere (CPU mesh
    included); on a no-overlap host the ratio is a floor, the bytes model
    is exact either way."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, shard_batch, softmax_xent,
    )
    from horovod_tpu.profiler import timed_steps

    try:
        hvd.init()
    except Exception as e:
        _emit_skip(f"tpu-unavailable: {type(e).__name__}", "zero_ab")
        return 0
    n = hvd.size()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = MLP()
    rng = jax.random.PRNGKey(0)
    batch = max(n * 8, 32)
    x_np = np.random.RandomState(0).rand(batch, 28, 28).astype(np.float32)
    y_np = np.random.RandomState(1).randint(0, 10, batch)
    sample = jnp.zeros((1, 28, 28), jnp.float32)
    variables = model.init(rng, sample)
    params0 = variables.get("params", variables)
    iters = max(args.iters, 5)

    def run(mode):
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        if mode == "sharded":
            tx = hvd.DistributedOptimizer(
                optax.adam(1e-3), shard_optimizer=True)
            step = make_shardmap_train_step(
                model, tx, loss_fn=softmax_xent, shard_optimizer=True,
                instrument=False)
        else:
            tx = optax.adam(1e-3)
            step = make_shardmap_train_step(
                model, tx, loss_fn=softmax_xent, instrument=False)
        opt_state = tx.init(params)
        if mode != "sharded":
            opt_state = replicate(opt_state)
        xs, ys = shard_batch(x_np), shard_batch(y_np)
        state = [params, {}, opt_state]
        for _ in range(3):  # warmup / compile
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
        jax.block_until_ready(state[0])

        def one():
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
            return loss

        losses, dt = timed_steps(one, iters)
        assert all(np.isfinite(l) for l in losses), losses[-3:]
        bytes_now = hvd.metrics.value(
            "grad_sync_bytes_per_step", mode=mode)
        return dt / iters, bytes_now

    t_ar, b_ar = run("allreduce")
    t_sh, b_sh = run("sharded")
    ratio = t_sh / t_ar if t_ar else None
    if hvd.metrics.enabled():
        hvd.metrics.gauge(
            "zero1_ab_step_ratio",
            help="sharded / allreduce step time (explicit-collective A/B)",
        ).set(ratio)
    out = {
        "metric": "zero1_sharded_vs_allreduce_step_ratio",
        "value": round(ratio, 4) if ratio is not None else None,
        "unit": "x",
        "n_chips": n,
        "allreduce_step_s": round(t_ar, 6),
        "sharded_step_s": round(t_sh, 6),
        "grad_sync_bytes_per_step": {"allreduce": b_ar, "sharded": b_sh},
        "grad_bytes_halved": (
            bool(b_ar and b_sh and b_sh <= 0.55 * b_ar)
        ),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _fsdp_byte_model(n: int) -> dict:
    """Analytic ZeRO-3-vs-ZeRO-1 wire bytes for the A/B MLP — emitted even
    when no device comes up (the byte model is exact on any mesh; only the
    step-time ratio needs live hardware)."""
    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    from scaling_projection import zero3_sync_bytes

    fp32 = zero3_sync_bytes(_AB_SHAPES, n)
    i8 = zero3_sync_bytes(_AB_SHAPES, n, wire="int8")
    return {
        "zero3_total_bytes": {"none": fp32["zero3_total"],
                              "int8": i8["zero3_total"]},
        "param_gather_bytes": {"none": fp32["param_gather"],
                               "int8": i8["param_gather"]},
        "grad_reduce_scatter_bytes": fp32["grad_reduce_scatter"],
        "zero1_total_bytes": fp32["zero1_total"],
        "wire_ratio_vs_zero1": {
            "none": round(fp32["zero3_total"] / fp32["zero1_total"], 4)
            if fp32["zero1_total"] else 0.0,
            "int8": round(i8["zero3_total"] / fp32["zero1_total"], 4)
            if fp32["zero1_total"] else 0.0,
        },
    }


def _run_fsdp_ab(args):
    """ZeRO-3 vs ZeRO-1 A/B rung: the same small MLP through the explicit-
    collective step with gather-on-use param sharding
    (``DistributedOptimizer(shard_params=True)``) vs the ZeRO-1 sharded
    optimizer, plus the measured ``param_gather_bytes_per_step`` /
    ``grad_sync_bytes_per_step`` gauges and the analytic
    ``zero3_sync_bytes`` model. Records ``fsdp_ab_step_ratio`` and prints
    ONE JSON line. CPU-safe; with no healthy device it still emits the
    byte-model line."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    def _emit_model_only(reason, n=8):
        out = {
            "metric": "fsdp_ab_step_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "byte_model": _fsdp_byte_model(n),
        }
        print(json.dumps(out), flush=True)

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.profiler import timed_steps
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, shard_batch, softmax_xent,
    )

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0
    n = hvd.size()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = MLP()
    batch = max(n * 8, 32)
    x_np = np.random.RandomState(0).rand(batch, 28, 28).astype(np.float32)
    y_np = np.random.RandomState(1).randint(0, 10, batch)
    sample = jnp.zeros((1, 28, 28), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), sample)
    params0 = variables.get("params", variables)
    iters = max(args.iters, 5)

    def run(mode):
        params = jax.tree_util.tree_map(jnp.array, params0)
        if mode == "zero3":
            params = hvd.fsdp_pack_params(params)
            tx = hvd.DistributedOptimizer(
                optax.adam(1e-3), shard_params=True)
            step = make_shardmap_train_step(
                model, tx, loss_fn=softmax_xent, shard_params=True,
                instrument=False)
        else:
            tx = hvd.DistributedOptimizer(
                optax.adam(1e-3), shard_optimizer=True)
            step = make_shardmap_train_step(
                model, tx, loss_fn=softmax_xent, shard_optimizer=True,
                instrument=False)
            params = replicate(params)
        opt_state = tx.init(params)
        xs, ys = shard_batch(x_np), shard_batch(y_np)
        state = [params, {}, opt_state]
        for _ in range(3):  # warmup / compile
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
        jax.block_until_ready(jax.tree_util.tree_leaves(state[0]))

        def one():
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
            return loss

        losses, dt = timed_steps(one, iters)
        assert all(np.isfinite(l) for l in losses), losses[-3:]
        metric_mode = "zero3" if mode == "zero3" else "sharded"
        return dt / iters, hvd.metrics.value(
            "grad_sync_bytes_per_step", mode=metric_mode)

    t_z1, b_z1 = run("zero1")
    t_z3, b_z3 = run("zero3")
    gather_bytes = hvd.metrics.value(
        "param_gather_bytes_per_step", mode="zero3")
    ratio = t_z3 / t_z1 if t_z1 else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "fsdp_ab_step_ratio",
            help="ZeRO-3 / ZeRO-1 step time (explicit-collective A/B)",
        ).set(ratio)
    out = {
        "metric": "fsdp_ab_step_ratio",
        "value": round(ratio, 4) if ratio is not None else None,
        "unit": "x",
        "n_chips": n,
        "zero1_step_s": round(t_z1, 6),
        "zero3_step_s": round(t_z3, 6),
        "grad_sync_bytes_per_step": {"zero1": b_z1, "zero3": b_z3},
        "param_gather_bytes_per_step": gather_bytes,
        "byte_model": _fsdp_byte_model(n),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _resolve_compression(args):
    """(compressor, error_feedback, name) from --compression /
    --fp16-allreduce. int8 and powersgd pair with error feedback — the
    convergence-safe configuration the docs recommend; fp16 keeps its
    historical EF-less spelling for baseline comparability."""
    from horovod_tpu.compression import Compression

    name = args.compression or ("fp16" if args.fp16_allreduce else "none")
    if name == "powersgd":
        return Compression.powersgd(args.powersgd_rank), True, name
    comp = {"none": Compression.none, "fp16": Compression.fp16,
            "int8": Compression.int8}[name]
    return comp, name == "int8", name


#: param shapes of the compression-ab MLP (28*28 -> 512 -> 512 -> 10), the
#: input to the byte models when no device ever comes up
_AB_SHAPES = [(784, 512), (512,), (512, 512), (512,), (512, 10), (10,)]


def _compression_byte_model(n: int, rank: int) -> dict:
    """Analytic per-mode wire bytes for the A/B model — emitted even when
    the device never produces a healthy window, so the round's perf
    trajectory records the byte A/B regardless (the CPU-mesh model is
    exact; only the step-time ratio needs a live mesh)."""
    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    from scaling_projection import (
        int8_sync_bytes, powersgd_sync_bytes, zero1_sync_bytes,
    )

    import numpy as _np

    elems = sum(int(_np.prod(s)) for s in _AB_SHAPES)
    fp32 = zero1_sync_bytes(4 * elems, n)
    fp16 = zero1_sync_bytes(4 * elems, n, wire_bytes=2 * elems)
    i8 = int8_sync_bytes(_AB_SHAPES, n)
    ps = powersgd_sync_bytes(_AB_SHAPES, rank, n)
    return {
        "grad_elems": elems,
        "rs_bytes": {
            "none": fp32["rs"], "fp16": fp16["rs"], "int8": i8["rs"],
            # P/Q ride full ring allreduces — the model's allreduce figure
            "powersgd": ps["allreduce"],
        },
        "wire_ratio_vs_fp32": {
            "none": 1.0, "fp16": 0.5,
            "int8": round(i8["ratio_vs_fp32"], 4),
            # powersgd vs the fp32 RS leg: its allreduce total over fp32's
            # one-way reduce-scatter bytes
            "powersgd": round(ps["allreduce"] / fp32["rs"], 4)
            if fp32["rs"] else 0.0,
        },
        "powersgd_rank": rank,
    }


def _run_compression_ab(args):
    """Compression A/B rung: the same small MLP through the ZeRO-1
    explicit-collective step under none / fp16 / int8 / powersgd wire
    compression. Records per-mode ``compression_ab_step_ratio`` gauges
    (mode step time / uncompressed step time) plus the measured
    ``grad_sync_bytes_per_step`` gauges, and prints ONE JSON line. Runs
    anywhere (CPU mesh included: the byte model is exact there, the time
    ratio a floor); if no backend comes up at all, the byte-model line is
    emitted anyway so the perf trajectory is never empty."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    rank = args.powersgd_rank or int(
        os.environ.get("HOROVOD_POWERSGD_RANK", "4"))

    def _emit_model_only(reason, n=8):
        out = {
            "metric": "compression_ab_step_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "byte_model": _compression_byte_model(n, rank),
        }
        print(json.dumps(out), flush=True)

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.compression import Compression
    from horovod_tpu.profiler import timed_steps
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, shard_batch, softmax_xent,
    )

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0
    n = hvd.size()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = MLP()
    batch = max(n * 8, 32)
    x_np = np.random.RandomState(0).rand(batch, 28, 28).astype(np.float32)
    y_np = np.random.RandomState(1).randint(0, 10, batch)
    sample = jnp.zeros((1, 28, 28), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), sample)
    params0 = variables.get("params", variables)
    iters = max(args.iters, 5)
    modes = {
        "none": (Compression.none, False),
        "fp16": (Compression.fp16, True),
        "int8": (Compression.int8, True),
        "powersgd": (Compression.powersgd(rank), True),
    }

    def run(comp, ef):
        tx = hvd.DistributedOptimizer(
            optax.adam(1e-3), shard_optimizer=True, compression=comp,
            error_feedback=ef)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, shard_optimizer=True,
            instrument=False)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        opt_state = tx.init(params)
        xs, ys = shard_batch(x_np), shard_batch(y_np)
        state = [params, {}, opt_state]
        for _ in range(3):  # warmup / compile
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
        jax.block_until_ready(state[0])

        def one():
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
            return loss

        losses, dt = timed_steps(one, iters)
        assert all(np.isfinite(l) for l in losses), losses[-3:]
        return dt / iters, hvd.metrics.value(
            "grad_sync_bytes_per_step", mode="sharded")

    step_s, sync_bytes, ratios = {}, {}, {}
    for name, (comp, ef) in modes.items():
        step_s[name], sync_bytes[name] = run(comp, ef)
        ratios[name] = (
            round(step_s[name] / step_s["none"], 4)
            if step_s.get("none") else None
        )
        if hvd.metrics.enabled() and ratios[name] is not None:
            hvd.metrics.gauge(
                "compression_ab_step_ratio",
                help="compressed / uncompressed step time "
                     "(explicit-collective ZeRO-1 A/B)",
                compression=name,
            ).set(ratios[name])
    out = {
        "metric": "compression_ab_step_ratio",
        "value": ratios.get("int8"),
        "unit": "x",
        "n_chips": n,
        "step_s": {k: round(v, 6) for k, v in step_s.items()},
        "step_ratio_vs_none": ratios,
        "grad_sync_bytes_per_step": sync_bytes,
        "byte_model": _compression_byte_model(n, rank),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _overlap_model(n: int, bucket_bytes: int, batch: int) -> dict:
    """Analytic overlap model for the A/B MLP — emitted even when no
    device comes up. Byte side (exact on any mesh): bucketing moves the
    same gradient bytes as the monolithic packing (per-bucket ZeRO
    padding is the only delta, reported). Time side (a projection, not a
    measurement): ``overlap_step_time`` evaluated at the TPU v4
    operating point — ring comm time for the model's gradient bytes over
    ICI vs its fwd+bwd FLOPs at peak."""
    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    from scaling_projection import _HW, overlap_step_time, zero1_sync_bytes

    from horovod_tpu.ops.overlap import BucketPlan

    import jax as _jax
    import numpy as _np

    leaves = [_jax.ShapeDtypeStruct(s, _np.float32) for s in _AB_SHAPES]
    elems = sum(int(_np.prod(s)) for s in _AB_SHAPES)
    grad_bytes = 4 * elems
    plan1 = BucketPlan.build(leaves, n=1, bucket_bytes=bucket_bytes)
    plan_n = BucketPlan.build(leaves, n=n, bucket_bytes=bucket_bytes)
    mono = zero1_sync_bytes(grad_bytes, n)
    # per-bucket ZeRO padding: the only wire-byte delta bucketing adds
    pad_bytes = 4 * sum(b.Lp - b.L for b in plan_n.buckets) \
        - 4 * ((-elems) % n)
    hw = _HW["tpu-v4"]
    flops = 6 * batch * sum(
        int(_np.prod(s)) for s in _AB_SHAPES if len(s) == 2)
    t_compute = flops / hw["peak_flops"]
    t_comm = mono["allreduce"] / hw["ici_bw"]
    return {
        "grad_bytes": grad_bytes,
        "bucketed_bytes": 4 * sum(b.L for b in plan1.buckets),
        "bucket_pad_bytes_vs_monolithic": pad_bytes,
        "n_buckets": len(plan1.buckets),
        "bucket_bytes": bucket_bytes,
        "projection_v4": overlap_step_time(
            t_compute, t_comm, len(plan1.buckets), latency_s=1e-6),
    }


def _run_overlap_ab(args):
    """Comm/compute-overlap A/B rung: the same small MLP through the
    explicit-collective ZeRO-1 step with bucketed (overlap) vs
    monolithic gradient sync. Records the ``overlap_ab_step_ratio``
    gauge (bucketed / monolithic step time), both modes' measured
    ``grad_sync_bytes_per_step`` + the ``grad_sync_buckets`` gauge, and
    prints ONE JSON line with the analytic
    ``overlap_step_time`` model. Runs anywhere — the 8-device CPU mesh
    timeshares one core, so the measured ratio there is an overhead
    floor (~1.0), never a speedup; the byte parity and the bucket count
    are exact on any mesh, and with no backend at all the analytic line
    is still emitted."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    bucket_bytes = args.bucket_bytes or int(os.environ.get(
        "HOROVOD_BUCKET_BYTES", str(256 * 1024)))

    def _emit_model_only(reason, n=8, batch=64):
        out = {
            "metric": "overlap_ab_step_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "overlap_model": _overlap_model(n, bucket_bytes, batch),
        }
        print(json.dumps(out), flush=True)

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.profiler import timed_steps
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, shard_batch, softmax_xent,
    )

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0
    n = hvd.size()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = MLP()
    batch = max(n * 8, 32)
    x_np = np.random.RandomState(0).rand(batch, 28, 28).astype(np.float32)
    y_np = np.random.RandomState(1).randint(0, 10, batch)
    sample = jnp.zeros((1, 28, 28), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), sample)
    params0 = variables.get("params", variables)
    iters = max(args.iters, 5)

    def run(overlap):
        # overlap=False explicitly: with HOROVOD_OVERLAP=1 exported (the
        # very knob this rung documents) an unset kwarg would bucket the
        # BASELINE arm too and the A/B would measure nothing
        kw = dict(shard_optimizer=True, overlap=False)
        if overlap:
            kw.update(overlap=True, bucket_bytes=bucket_bytes)
        tx = hvd.DistributedOptimizer(optax.adam(1e-3), **kw)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, shard_optimizer=True,
            instrument=False)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        opt_state = tx.init(params)
        xs, ys = shard_batch(x_np), shard_batch(y_np)
        state = [params, {}, opt_state]
        for _ in range(3):  # warmup / compile
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
        jax.block_until_ready(state[0])

        def one():
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
            return loss

        losses, dt = timed_steps(one, iters)
        assert all(np.isfinite(l) for l in losses), losses[-3:]
        return dt / iters, hvd.metrics.value(
            "grad_sync_bytes_per_step", mode="sharded"), hvd.metrics.value(
            "grad_sync_buckets", mode="sharded")

    t_mono, b_mono, k_mono = run(False)
    t_ov, b_ov, k_ov = run(True)
    ratio = t_ov / t_mono if t_mono else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "overlap_ab_step_ratio",
            help="bucketed / monolithic step time (explicit-collective "
                 "ZeRO-1 A/B)",
        ).set(ratio)
    out = {
        "metric": "overlap_ab_step_ratio",
        "value": round(ratio, 4) if ratio is not None else None,
        "unit": "x",
        "n_chips": n,
        "monolithic_step_s": round(t_mono, 6),
        "bucketed_step_s": round(t_ov, 6),
        "grad_sync_bytes_per_step": {"monolithic": b_mono, "bucketed": b_ov},
        "grad_sync_buckets": {"monolithic": k_mono, "bucketed": k_ov},
        "overlap_model": _overlap_model(n, bucket_bytes, batch),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


#: the --pallas-ab workload tree: one fat f32 matrix + biases, small
#: enough that the off-TPU interpret-mode arm stays in CI budget while
#: the flat ZeRO packing still quantizes (above the 1024-element floor)
_PALLAS_AB_SHAPES = [(784, 64), (64,), (64, 10), (10,)]


def _pallas_byte_model(n: int = 8) -> dict:
    """Analytic HBM-traffic model for the Pallas A/B — emitted even when
    no device comes up (exact on any mesh: it depends only on shapes)."""
    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    from scaling_projection import pallas_hot_path_bytes

    return pallas_hot_path_bytes(
        _PALLAS_AB_SHAPES, n, error_feedback=True, epilogue="scatter")


def _run_pallas_ab(args):
    """Pallas-kernel A/B rung: the same small MLP through the ZeRO-1 +
    int8 + error-feedback + fused-Adam step with ``HOROVOD_PALLAS=1``
    (fused kernels) vs ``=0`` (discrete HLO). Records the
    ``pallas_ab_step_ratio`` gauge (fused / discrete step time), both
    arms' billed ``grad_sync_bytes_per_step``, and prints ONE JSON line
    with the analytic ``pallas_hot_path_bytes`` HBM model plus the
    ring-model wire bytes the gauges should equal. The byte gauges are
    the trace-time per-leaf wire-pricing model, identical across arms
    by construction — they pin that both programs BILL the same wire,
    not that the compiled wire is unchanged; the schedule-fingerprint
    matrix (tests/test_pallas.py) is what pins wire invariance. Runs
    anywhere: off-TPU the fused arm executes the kernels in Pallas
    INTERPRET mode — the equivalence surface, so the CPU time ratio
    measures interpreter overhead plus millisecond-scale timing noise
    (usually > 1, occasionally < 1 on the timeshared mesh) and is never
    a perf signal either way — and with no backend at all the analytic
    line still emits."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    def _emit_model_only(reason, n=8):
        out = {
            "metric": "pallas_ab_step_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "pallas_model": _pallas_byte_model(n),
        }
        print(json.dumps(out), flush=True)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.compression import Compression, Int8Compressor
    from horovod_tpu.ops.collective import _smap, allreduce, Average
    from horovod_tpu.profiler import timed_steps
    from horovod_tpu.training import shard_batch
    from jax.sharding import PartitionSpec as P

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0
    n = hvd.size()
    ax = hvd.data_axis()
    mesh = hvd.mesh()

    rng = np.random.RandomState(0)
    params0 = {
        "w1": jnp.asarray(rng.randn(784, 64).astype(np.float32) * 0.05),
        "b1": jnp.zeros((64,), jnp.float32),
        "w2": jnp.asarray(rng.randn(64, 10).astype(np.float32) * 0.05),
        "b2": jnp.zeros((10,), jnp.float32),
    }
    x_np = rng.rand(max(n * 4, 16), 784).astype(np.float32)
    y_np = rng.randn(x_np.shape[0], 10).astype(np.float32)
    # interpret mode pays per-grid-step interpreter overhead, so the
    # measured arm stays short OFF-TPU only; a TPU run honors --iters
    iters = max(args.iters, 3)
    if jax.default_backend() != "tpu":
        iters = min(iters, 10)

    def loss_fn(p, x, y):
        h = jnp.maximum(x @ p["w1"] + p["b1"][None], 0.0)
        return jnp.mean((h @ p["w2"] + p["b2"][None] - y) ** 2)

    def run(pallas: str):
        prev = os.environ.get("HOROVOD_PALLAS")
        os.environ["HOROVOD_PALLAS"] = pallas
        try:
            tx = hvd.DistributedOptimizer(
                hvd.fused_adam(1e-3), compression=Compression.int8,
                error_feedback=True, shard_optimizer=True)
            params = jax.tree_util.tree_map(jnp.array, params0)
            state = tx.init(params)

            def step(p, s, x, y):
                l, g = jax.value_and_grad(loss_fn)(p, x, y)
                u, s = tx.update(g, s, p)
                p = optax.apply_updates(p, u)
                return p, s, allreduce(l, Average, axis=ax)

            sm = jax.jit(_smap(
                step, mesh, (P(), P(ax), P(ax), P(ax)), (P(), P(ax), P())
            ))
            xs, ys = shard_batch(x_np), shard_batch(y_np)
            box = [params, state]
            for _ in range(2):  # warmup / compile
                box[0], box[1], loss = sm(box[0], box[1], xs, ys)
            jax.block_until_ready(box[0])

            def one():
                box[0], box[1], loss = sm(box[0], box[1], xs, ys)
                return loss

            losses, dt = timed_steps(one, iters)
            assert all(np.isfinite(l) for l in losses), losses[-3:]
            return dt / iters, hvd.metrics.value(
                "grad_sync_bytes_per_step", mode="sharded")
        finally:
            if prev is None:
                os.environ.pop("HOROVOD_PALLAS", None)
            else:
                os.environ["HOROVOD_PALLAS"] = prev

    t_disc, b_disc = run("0")
    t_fused, b_fused = run("1")
    ratio = t_fused / t_disc if t_disc else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "pallas_ab_step_ratio",
            help="fused-Pallas / discrete-HLO step time (ZeRO-1 + int8 + "
                 "fused-Adam A/B; interpreter overhead off-TPU)",
        ).set(ratio)
    # the ring-model wire bytes both gauges should equal: ONE f32 flat
    # group of Lp = E padded to the axis size, priced by the compressor
    elems = sum(
        int(np.prod(s)) for s in _PALLAS_AB_SHAPES)
    lp = elems + ((-elems) % n)
    ring = (n - 1) / n if n > 1 else 0.0
    wire_model = ring * Int8Compressor.wire_bytes((lp,), jnp.float32)
    out = {
        "metric": "pallas_ab_step_ratio",
        "value": round(ratio, 4) if ratio is not None else None,
        "unit": "x",
        "n_chips": n,
        "discrete_step_s": round(t_disc, 6),
        "fused_step_s": round(t_fused, 6),
        "interpret": jax.default_backend() != "tpu",
        "grad_sync_bytes_per_step": {
            "discrete": b_disc, "fused": b_fused,
            "ring_model": wire_model,
        },
        "pallas_model": _pallas_byte_model(n),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _publish_byte_model(keyframe_every: int = 8) -> dict:
    """Analytic publish bytes for the A/B model — emitted even when no
    device comes up, so the round's perf trajectory always records the
    delta+int8 vs full-checkpoint comparison (exact on any mesh)."""
    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    from scaling_projection import publish_bytes

    return publish_bytes(_AB_SHAPES, keyframe_every=keyframe_every)


def _run_publish_ab(args):
    """Weight-publication A/B rung: the same small MLP stepped with
    streaming weight publication ON (every step, int8 deltas + periodic
    keyframes to an in-process KV) vs OFF. Records the
    ``publish_ab_step_ratio`` gauge (published / bare step time), the
    measured ``serving_publish_wire_bytes`` gauges, and ONE JSON line with
    the analytic delta-vs-full-checkpoint byte model. A subscriber polls
    every generation and the run asserts it reconstructs the trainer's
    weights — the rung doubles as an end-to-end protocol check. Runs
    anywhere (CPU mesh included; the byte model is exact there, the time
    ratio an upper bound — publication is host-side work)."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    keyframe_every = 8

    def _emit_model_only(reason):
        out = {
            "metric": "publish_ab_step_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "byte_model": _publish_byte_model(keyframe_every),
        }
        print(json.dumps(out), flush=True)

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu import checkpoint as _ckpt
    from horovod_tpu.profiler import timed_steps
    from horovod_tpu.run.rendezvous import KVStoreServer
    from horovod_tpu.serving import WeightPublisher, WeightSubscriber
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, shard_batch, softmax_xent,
    )

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0
    n = hvd.size()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            x = nn.Dense(512)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = MLP()
    batch = max(n * 8, 32)
    x_np = np.random.RandomState(0).rand(batch, 28, 28).astype(np.float32)
    y_np = np.random.RandomState(1).randint(0, 10, batch)
    sample = jnp.zeros((1, 28, 28), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), sample)
    params0 = variables.get("params", variables)
    iters = max(args.iters, 5)
    server = KVStoreServer()

    def run(publisher):
        tx = hvd.DistributedOptimizer(optax.adam(1e-3))
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, instrument=False)
        params = replicate(jax.tree_util.tree_map(jnp.array, params0))
        opt_state = tx.init(params)
        xs, ys = shard_batch(x_np), shard_batch(y_np)
        state = [params, {}, opt_state]
        for _ in range(3):  # warmup / compile
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
        jax.block_until_ready(state[0])
        counter = {"step": 0}

        def one():
            state[0], state[1], state[2], loss = step(
                state[0], state[1], state[2], xs, ys)
            counter["step"] += 1
            if publisher is not None:
                publisher.publish({"params": state[0]}, counter["step"])
            else:
                float(loss)  # fence: match the publisher's D2H sync cost
            return loss

        losses, dt = timed_steps(one, iters)
        assert all(np.isfinite(l) for l in losses), losses[-3:]
        return dt / iters, state[0]

    bare_s, _ = run(None)
    pub = WeightPublisher(
        server, keyframe_every=keyframe_every, register=False)
    pub_s, final_params = run(pub)
    ratio = round(pub_s / bare_s, 4) if bare_s else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "publish_ab_step_ratio",
            help="published / bare step time (streaming weight "
                 "publication every step)",
        ).set(ratio)

    # protocol self-check: a subscriber reconstructs the trainer's weights
    sub = WeightSubscriber(server)
    tree = sub.wait_for_generation(pub.generation, timeout=30)
    for got, want in zip(
        jax.tree_util.tree_leaves(tree),
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(
            np.asarray, final_params)),
    ):
        np.testing.assert_allclose(got, want, atol=5e-2)

    ckpt_bytes = _ckpt.state_nbytes(final_params)
    out = {
        "metric": "publish_ab_step_ratio",
        "value": ratio,
        "unit": "x",
        "n_chips": n,
        "step_s": {"bare": round(bare_s, 6), "published": round(pub_s, 6)},
        "generations": pub.generation,
        "subscriber_generation": sub.generation,
        "publish_wire_bytes": {
            "key": hvd.metrics.value(
                "serving_publish_wire_bytes", kind="key"),
            "delta": hvd.metrics.value(
                "serving_publish_wire_bytes", kind="delta"),
        },
        "checkpoint_bytes": ckpt_bytes,
        "byte_model": _publish_byte_model(keyframe_every),
        "device_kind": jax.devices()[0].device_kind,
    }
    server.close()
    print(json.dumps(out), flush=True)
    return 0


def _run_serving_ab(args):
    """Serving-engine A/B rung: one ragged request set decoded twice —
    (a) through the continuous-batching paged engine (sequences join at
    iteration boundaries, finished slots readmit immediately, prefill
    chunked into the decode schedule) and (b) as one static right-padded
    ``generate()`` batch that holds every row until the whole wave
    finishes. Records ``serving_ab_goodput_ratio`` (engine goodput /
    static goodput, generated tokens per second) and prints ONE JSON line
    beside the analytic slot-token model
    (``tools/scaling_projection.py::serving_goodput``). Both arms run
    compile-warm (the engine is reused across runs; the static waves are
    jitted per shape), so the measured CPU ratio is an honest FLOOR: on
    millisecond steps the engine's per-iteration host scheduling and
    logits readback dominate and the ratio lands well under 1 — the
    padded-work saving the model prices needs accelerator-scale step
    times to show up. The run also asserts the engine's greedy tokens
    match ``generate()`` exactly — the rung doubles as an end-to-end
    parity check."""
    import numpy as np

    from tools.scaling_projection import serving_goodput

    max_new = 8
    max_batch = 4
    prefill_chunk = 8
    rng = np.random.RandomState(0)
    prompt_lens = [int(x) for x in rng.randint(4, 25, size=12)]

    def _emit_model_only(reason):
        out = {
            "metric": "serving_ab_goodput_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "goodput_model": serving_goodput(
                prompt_lens, max_new, max_batch=max_batch,
                prefill_chunk=prefill_chunk),
        }
        print(json.dumps(out), flush=True)

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0

    from horovod_tpu.models.transformer import TransformerLM, generate
    from horovod_tpu.serving.engine import InferenceEngine

    model = TransformerLM(vocab=256, dim=64, depth=2, heads=4, mlp_ratio=2,
                          max_len=64, dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompts = [rng.randint(1, 256, size=l).astype(np.int32)
               for l in prompt_lens]

    # static arm: ceil(R / B) right-padded generate() waves. The wave fn
    # is jitted (one compile per wave shape, cached across runs) so BOTH
    # arms are compile-warm in the timed passes and the ratio measures
    # scheduling, not trace/lowering overhead.
    static_fns = {}

    def _static_fn(shape):
        if shape not in static_fns:
            static_fns[shape] = jax.jit(
                lambda p, pad, lens: generate(
                    model, p, pad, max_new_tokens=max_new,
                    prompt_lens=lens))
        return static_fns[shape]

    def run_static():
        outs = []
        for i in range(0, len(prompts), max_batch):
            wave = prompts[i:i + max_batch]
            tmax = max(len(p) for p in wave)
            pad = np.zeros((len(wave), tmax), np.int32)
            for j, p in enumerate(wave):
                pad[j, :len(p)] = p
            lens = np.asarray([len(p) for p in wave], np.int32)
            toks = np.asarray(_static_fn(pad.shape)(
                params, jnp.asarray(pad), jnp.asarray(lens)))
            outs.extend(
                toks[j, lens[j]:lens[j] + max_new]
                for j in range(len(wave)))
        return outs

    # ONE engine across warmup + timed runs: a fresh engine per run would
    # carry a fresh jit cache, so the timed arm would re-trace and
    # re-compile while the static arm stays warm — deflating the ratio
    eng = InferenceEngine(
        model, page_size=8, num_pages=64, max_batch=max_batch,
        prefill_chunk=prefill_chunk, max_seq_len=40)
    eng.set_weights(params, generation=1)

    def run_engine():
        reqs = [eng.submit(p, max_new, rid=f"ab-{i}")
                for i, p in enumerate(prompts)]
        eng.run_until_idle()
        return [np.asarray(r.generated) for r in reqs]

    # warmup both arms (compiles dominate a first pass)
    static_out = run_static()
    engine_out = run_engine()
    for a, b in zip(engine_out, static_out):
        np.testing.assert_array_equal(a, b)

    total_new = len(prompts) * max_new
    t0 = time.perf_counter()
    run_static()
    static_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_engine()
    engine_s = time.perf_counter() - t0
    ratio = round((total_new / engine_s) / (total_new / static_s), 4) \
        if engine_s and static_s else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "serving_ab_goodput_ratio",
            help="continuous-batching engine goodput / static batched "
                 "generate() goodput (tokens per second)",
        ).set(ratio)
    out = {
        "metric": "serving_ab_goodput_ratio",
        "value": ratio,
        "unit": "x",
        "n_requests": len(prompts),
        "max_new_tokens": max_new,
        "wall_s": {"static": round(static_s, 6),
                   "engine": round(engine_s, 6)},
        "goodput_tokens_per_s": {
            "static": round(total_new / static_s, 2),
            "engine": round(total_new / engine_s, 2),
        },
        "goodput_model": serving_goodput(
            prompt_lens, max_new, max_batch=max_batch,
            prefill_chunk=prefill_chunk),
        "parity": "token-identical",
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _run_prefix_ab(args):
    """Prefix-cache A/B rung: the same ragged request set served twice
    through ONE engine — first cold (every prompt pays full prefill, and
    its full prompt pages enter the refcounted index at finish), then
    cached (admission aliases the resident pages and prefills only the
    non-shared tail). Records ``prefix_ab_prefill_ratio`` (cold wall /
    cached wall for the full drain) and prints ONE JSON line beside the
    analytic ``tools/scaling_projection.py::prefix_prefill_flops``
    model. The measured ``serving_prefill_tokens`` deltas must match the
    model EXACTLY — the model replicates the engine's hit rounding
    (lcm(page, chunk) alignment, capped below the prompt end), so any
    drift is a real caching bug. Tokens from the cached pass must be
    bit-identical to the cold pass (and both to ``generate()`` — the
    cold pass rides the same parity-pinned engine)."""
    import numpy as np

    from tools.scaling_projection import prefix_prefill_flops

    max_new = 8
    max_batch = 4
    prefill_chunk = 8
    page_size = 8
    rng = np.random.RandomState(0)
    prompt_lens = [int(x) for x in rng.randint(10, 33, size=10)]
    model_line = prefix_prefill_flops(
        prompt_lens, prompt_lens, page_size=page_size,
        prefill_chunk=prefill_chunk)

    def _emit_model_only(reason):
        out = {
            "metric": "prefix_ab_prefill_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "prefill_model": model_line,
        }
        print(json.dumps(out), flush=True)

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0

    from horovod_tpu.models.transformer import TransformerLM
    from horovod_tpu.serving.engine import InferenceEngine

    model = TransformerLM(vocab=256, dim=64, depth=2, heads=4,
                          mlp_ratio=2, max_len=64, dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompts = [rng.randint(1, 256, size=l).astype(np.int32)
               for l in prompt_lens]
    eng = InferenceEngine(
        model, page_size=page_size, num_pages=128, max_batch=max_batch,
        prefill_chunk=prefill_chunk, max_seq_len=48, prefix_cache=True)
    eng.set_weights(params, generation=1)

    def run(batch, tag):
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new, rid=f"{tag}-{i}")
                for i, p in enumerate(batch)]
        eng.run_until_idle()
        return (time.perf_counter() - t0,
                [np.asarray(r.generated) for r in reqs])

    # compile warmup on a DIFFERENT prompt set (same lengths): both
    # measured passes run compile-warm, and the warmup prompts share no
    # prefix with the measured ones, so the measured cold pass is cold
    warmup = [rng.randint(1, 256, size=l).astype(np.int32)
              for l in prompt_lens]
    run(warmup, "warm")

    def tokens_counter():
        return hvd.metrics.value("serving_prefill_tokens") \
            if hvd.metrics.enabled() else None

    before = tokens_counter()
    cold_s, cold_toks = run(prompts, "cold")
    mid = tokens_counter()
    cached_s, cached_toks = run(prompts, "cached")
    after = tokens_counter()
    for a, b in zip(cached_toks, cold_toks):
        np.testing.assert_array_equal(a, b)
    measured_cold = measured_cached = None
    if before is not None:
        measured_cold = int(mid - before)
        measured_cached = int(after - mid)
        assert measured_cold == model_line["cold_prefill_tokens"], (
            measured_cold, model_line)
        assert measured_cached == model_line["cached_prefill_tokens"], (
            measured_cached, model_line)
    ratio = round(cold_s / cached_s, 4) if cached_s else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "prefix_ab_prefill_ratio",
            help="cold drain wall / prefix-cached drain wall for the "
                 "same request set (one engine, warm jit cache)",
        ).set(ratio)
    out = {
        "metric": "prefix_ab_prefill_ratio",
        "value": ratio,
        "unit": "x",
        "n_requests": len(prompts),
        "wall_s": {"cold": round(cold_s, 6),
                   "cached": round(cached_s, 6)},
        "measured_prefill_tokens": {"cold": measured_cold,
                                    "cached": measured_cached},
        "prefill_model": model_line,
        "parity": "token-identical",
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _run_spec_ab(args):
    """Speculative-decoding A/B rung: the same ragged request set decoded
    by a plain engine and by one speculating with a FULL-DEPTH draft —
    draft argmax ≡ target argmax, so acceptance is deterministically
    100% and the ``spec_proposed`` / ``spec_accepted`` counters must
    match ``tools/scaling_projection.py::spec_decode_tokens`` EXACTLY
    (each request: ``(max_new−1) // (K+1)`` speculative iterations of
    ``K+1`` tokens, remainder decoded plain). Records
    ``spec_ab_goodput_ratio`` (spec tokens/s over plain tokens/s; on CPU
    the draft's extra forwards usually land it under 1 — the model's
    ``decode_goodput_ratio`` prices the real win at ``draft_cost < 1``)
    and prints ONE JSON line. Both arms must be token-identical."""
    import numpy as np

    from tools.scaling_projection import spec_decode_tokens

    max_new = 10
    lookahead = 3
    n_requests = 8
    model_line = spec_decode_tokens(
        max_new, lookahead, acceptance_rate=1.0, draft_cost=1.0,
        n_requests=n_requests)

    def _emit_model_only(reason):
        out = {
            "metric": "spec_ab_goodput_ratio",
            "value": None,
            "unit": "x",
            "skipped": reason,
            "spec_model": model_line,
        }
        print(json.dumps(out), flush=True)

    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    try:
        hvd.init()
    except Exception as e:
        _emit_model_only(f"tpu-unavailable: {type(e).__name__}")
        return 0

    from horovod_tpu.models.transformer import TransformerLM
    from horovod_tpu.serving.engine import InferenceEngine

    model = TransformerLM(vocab=256, dim=64, depth=2, heads=4,
                          mlp_ratio=2, max_len=64, dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, size=int(l)).astype(np.int32)
               for l in rng.randint(4, 21, size=n_requests)]
    plain = InferenceEngine(
        model, page_size=8, num_pages=64, max_batch=4,
        prefill_chunk=8, max_seq_len=40)
    plain.set_weights(params, generation=1)
    # full-depth draft: acceptance is 100% by construction, making the
    # counter pin exact; a REAL deployment uses draft_depth << depth
    spec = InferenceEngine(
        model, page_size=8, num_pages=64, max_batch=4,
        prefill_chunk=8, max_seq_len=40, draft_depth=model.depth,
        spec_lookahead=lookahead)
    spec.set_weights(params, generation=1)

    def run(eng, tag):
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new, rid=f"{tag}-{i}")
                for i, p in enumerate(prompts)]
        eng.run_until_idle()
        return (time.perf_counter() - t0,
                [np.asarray(r.generated) for r in reqs])

    run(plain, "warm-p")
    run(spec, "warm-s")

    def cval(name):
        return hvd.metrics.value(name) if hvd.metrics.enabled() else None

    p0, a0 = cval("spec_proposed"), cval("spec_accepted")
    plain_s, plain_toks = run(plain, "plain")
    spec_s, spec_toks = run(spec, "spec")
    for a, b in zip(spec_toks, plain_toks):
        np.testing.assert_array_equal(a, b)
    measured_proposed = measured_accepted = None
    if p0 is not None:
        measured_proposed = int(cval("spec_proposed") - p0)
        measured_accepted = int(cval("spec_accepted") - a0)
        assert measured_proposed == model_line["proposed"], (
            measured_proposed, model_line)
        assert measured_accepted == model_line["accepted"], (
            measured_accepted, model_line)
    total_new = len(prompts) * max_new
    ratio = round((total_new / spec_s) / (total_new / plain_s), 4) \
        if spec_s and plain_s else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "spec_ab_goodput_ratio",
            help="speculative-decode goodput / plain-decode goodput "
                 "(tokens per second, full-depth draft)",
        ).set(ratio)
    out = {
        "metric": "spec_ab_goodput_ratio",
        "value": ratio,
        "unit": "x",
        "n_requests": len(prompts),
        "max_new_tokens": max_new,
        "lookahead": lookahead,
        "wall_s": {"plain": round(plain_s, 6),
                   "spec": round(spec_s, 6)},
        "measured": {"proposed": measured_proposed,
                     "accepted": measured_accepted},
        "spec_model": model_line,
        "parity": "token-identical",
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _run_straggler_ab(args):
    """Straggler A/B rung: time the same eager-collective step loop with
    and without an injected ``rank_slow`` chaos charge while the fleet
    aggregation plane (publisher → KV → rank-0 aggregator) attributes the
    straggler live. Records the ``straggler_ab_step_ratio`` gauge
    (slowed / clean step time — on a per-collective delay of D with C
    collectives per step the analytic expectation is
    ``1 + C·D/clean_step``) and prints ONE JSON line carrying the detected
    rank + measured arrival spread, so the rung doubles as an end-to-end
    check of the detection path. Runs anywhere (CPU mesh included)."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.observability import aggregate, straggler
    from horovod_tpu.resilience import chaos, health
    from horovod_tpu.run.rendezvous import KVStoreServer

    try:
        hvd.init()
    except Exception as e:
        _emit_skip(f"tpu-unavailable: {type(e).__name__}", "straggler_ab")
        return 0
    n = hvd.size()
    slow_rank = min(3, n - 1)
    delay = 0.05
    iters = max(args.iters, 5)
    collectives_per_step = 2
    x = np.random.RandomState(0).rand(256, 64).astype(np.float32)

    server = KVStoreServer()
    try:
        pub = aggregate.MetricsPublisher(server, rank=0, interval=60.0)
        agg = aggregate.FleetAggregator(server, register=False)

        def run(with_chaos):
            chaos.configure(
                f"rank_slow={slow_rank}:{delay}" if with_chaos else None
            )
            straggler.reset()
            health.reset()
            detected = None
            t0 = time.time()
            for step in range(iters):
                straggler.set_step(step)
                for _ in range(collectives_per_step):
                    np.asarray(hvd.allreduce(x, hvd.Sum))
                pub.publish_once()
                out = agg.collect()
                if out["straggler"] is not None and detected is None:
                    detected = dict(out["straggler"], at_step=step)
            return (time.time() - t0) / iters, detected

        clean_s, _ = run(False)
        slow_s, detected = run(True)
    finally:
        chaos.reset()
        server.close()
    ratio = round(slow_s / clean_s, 4) if clean_s else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "straggler_ab_step_ratio",
            help="rank_slow-injected / clean step time (straggler A/B)",
        ).set(ratio)
    out = {
        "metric": "straggler_ab_step_ratio",
        "value": ratio,
        "unit": "x",
        "n_chips": n,
        "clean_step_s": round(clean_s, 6),
        "slowed_step_s": round(slow_s, 6),
        "injected": {"rank": slow_rank, "seconds": delay},
        "expected_ratio": round(
            1.0 + collectives_per_step * delay / clean_s, 4
        ) if clean_s else None,
        "detected_rank": None if detected is None else detected["rank"],
        "detected_at_step": (
            None if detected is None else detected["at_step"]
        ),
        "detected_spread_s": (
            None if detected is None
            else round(detected["spread_seconds"], 6)
        ),
        "health": health.health_state().name,
    }
    print(json.dumps(out), flush=True)
    return 0


def _run_numerics_ab(args):
    """Numerics-guard A/B rung: run the same guarded explicit-collective
    train loop clean and under an injected ``grad_spike_at_step`` chaos
    charge. Records the ``numerics_ab_step_ratio`` gauge (spiked / clean
    step time — the guard's fused-reduction overhead is symmetric, so the
    expected ratio is ~1.0; the spiked run additionally proves the
    detector by reporting which step was marked BAD and skipped) and
    prints ONE JSON line with the detection step. Runs anywhere (CPU mesh
    included)."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.resilience import chaos, numerics
    from horovod_tpu.training import (
        make_shardmap_train_step, shard_batch, softmax_xent,
    )
    import flax.linen as nn

    try:
        hvd.init()
    except Exception as e:
        _emit_skip(f"tpu-unavailable: {type(e).__name__}", "numerics_ab")
        return 0

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(8)(nn.relu(nn.Dense(32)(x)))

    n = hvd.size()
    iters = max(args.iters, 10)
    spike_at = 7  # past the guard's 5-step EWMA warmup (+1 warmup call)
    spike_scale = 1e4
    model = Tiny()
    rng = np.random.RandomState(0)
    x = shard_batch(rng.rand(4 * n, 16).astype(np.float32))
    y = shard_batch(rng.randint(0, 8, 4 * n))
    params0 = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]

    def run(with_chaos):
        chaos.configure(
            f"grad_spike_at_step={spike_at}:{spike_scale}"
            if with_chaos else None
        )
        tx = hvd.DistributedOptimizer(
            optax.adam(1e-2), shard_optimizer=True, numerics_guard=True)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, shard_optimizer=True,
            instrument=False)
        params = jax.tree_util.tree_map(jnp.array, params0)
        opt_state = tx.init(params)
        # compile outside the clock (the step donates its inputs, so the
        # warmup's outputs become the loop's inputs)
        params, _, opt_state, _ = step(params, {}, opt_state, x, y)
        detected = None
        t0 = time.time()
        for i in range(iters):
            params, _, opt_state, loss = step(params, {}, opt_state, x, y)
            v = numerics.note_step(i, opt_state)
            if v is not None and v["last_bad"] and detected is None:
                # report on the guard-count clock — the charge's own
                # grammar: the out-of-clock warmup call consumed count 0,
                # so loop iteration i runs at guard count i+1 and a
                # correct detection equals `injected.step`
                detected = i + 1
        return (time.time() - t0) / iters, detected, numerics.verdict(
            opt_state)

    try:
        clean_s, _, _ = run(False)
        spiked_s, detected, v = run(True)
    finally:
        chaos.reset()
    ratio = round(spiked_s / clean_s, 4) if clean_s else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "numerics_ab_step_ratio",
            help="grad_spike-injected / clean guarded step time "
                 "(numerics A/B)",
        ).set(ratio)
    out = {
        "metric": "numerics_ab_step_ratio",
        "value": ratio,
        "unit": "x",
        "n_chips": n,
        "clean_step_s": round(clean_s, 6),
        "spiked_step_s": round(spiked_s, 6),
        "injected": {"step": spike_at, "scale": spike_scale},
        "detected_at_step": detected,
        "bad_steps": None if v is None else v["bad_count"],
        "grad_norm_ewma": None if v is None else round(v["ewma"], 6),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _run_input_ab(args):
    """Input-pipeline A/B rung: the same jitted step fed by a
    ResumableLoader with the prefetch thread on vs off (synchronous host
    gather per batch). The source charges a deterministic per-batch host
    load cost so the rung measures the *overlap machinery*, not tmpfs
    speed; the analytic ``input_step_time`` model (serial = compute +
    load, overlapped = max(compute, load)) is emitted beside the
    measurement — and alone when no device comes up. Records the
    ``input_ab_step_ratio`` gauge (serial / overlapped step time; >= 1
    when prefetch wins) and prints ONE JSON line."""
    root = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(root, "tools"))
    from scaling_projection import input_step_time

    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    load_cost_s = 0.002
    model_only = {
        "metric": "input_ab_step_ratio",
        "unit": "x",
        "input_model": input_step_time(0.004, load_cost_s, 2),
    }

    import numpy as np

    import horovod_tpu as hvd

    try:
        hvd.init()
    except Exception as e:
        _emit_skip(f"tpu-unavailable: {type(e).__name__}", "input_ab")
        model_only["skipped"] = True
        print(json.dumps(model_only), flush=True)
        return 0

    import jax
    import jax.numpy as jnp

    from horovod_tpu.data import ResumableLoader
    from horovod_tpu.data.loader import _ArraySource

    n = hvd.size()
    iters = max(args.iters, 10)
    rows, feat = 64 * n, 256
    rng = np.random.RandomState(0)
    X = rng.rand(rows, feat).astype(np.float32)
    Y = rng.randint(0, 8, rows).astype(np.int32)
    W = jnp.asarray(rng.rand(feat, feat).astype(np.float32))

    class _CostedSource(_ArraySource):
        """Array source with a deterministic per-gather host cost — the
        stand-in for a real storage read on the tmpfs-backed CI host."""

        def gather(self, indices):
            time.sleep(load_cost_s)
            return super().gather(indices)

    @jax.jit
    def step(w, xb):
        h = xb @ w
        for _ in range(8):
            h = jnp.tanh(h @ w)
        return h.sum()

    def run(prefetch):
        loader = ResumableLoader(
            _CostedSource((X, Y)), 8 * n, seed=0, prefetch=prefetch,
            name=f"input-ab-{prefetch}", register=False,
        )
        try:
            xb, _ = loader.next_batch()  # warm the jit outside the clock
            float(step(W, xb))
            t0 = time.time()
            for _ in range(iters):
                xb, _ = loader.next_batch()
                float(step(W, xb))
            return (time.time() - t0) / iters
        finally:
            loader.close()

    serial_s = run(0)
    overlapped_s = run(2)
    # the compute half alone (loader out of the loop), for the model
    xb, _ = ResumableLoader(
        (X, Y), 8 * n, seed=0, prefetch=0, name="input-ab-probe",
        register=False,
    ).next_batch()
    t0 = time.time()
    for _ in range(iters):
        float(step(W, xb))
    compute_s = (time.time() - t0) / iters

    ratio = round(serial_s / overlapped_s, 4) if overlapped_s else None
    if hvd.metrics.enabled() and ratio is not None:
        hvd.metrics.gauge(
            "input_ab_step_ratio",
            help="prefetch-off / prefetch-on step time (input A/B)",
        ).set(ratio)
    out = {
        "metric": "input_ab_step_ratio",
        "value": ratio,
        "unit": "x",
        "n_chips": n,
        "serial_step_s": round(serial_s, 6),
        "overlapped_step_s": round(overlapped_s, 6),
        "compute_step_s": round(compute_s, 6),
        "load_cost_s": load_cost_s,
        "input_model": input_step_time(compute_s, load_cost_s, 2),
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _run_elastic_chaos(args):
    """Elastic chaos soak: train a small ZeRO-1 explicit-collective model
    under ``rank_fail``/``rank_join`` chaos — the coordinator shrinks the
    mesh mid-run and grows it back — and report the measured recovery
    latency (rollback + mesh re-formation + reshard + epoch barrier) as
    the ``elastic_recovery_latency_seconds`` gauge plus ONE JSON line.
    Runs anywhere (CPU mesh included)."""
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.resilience import chaos, elastic
    from horovod_tpu.training import (
        make_shardmap_train_step, replicate, shard_batch, softmax_xent,
    )

    try:
        hvd.init()
    except Exception as e:
        _emit_skip(f"tpu-unavailable: {type(e).__name__}", "elastic_chaos")
        return 0
    n0 = hvd.size()
    if n0 < 3:
        _emit_skip(f"needs >= 3 ranks, have {n0}", "elastic_chaos")
        return 0

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(256)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = MLP()
    sample = jnp.zeros((1, 28, 28), jnp.float32)
    params0 = model.init(jax.random.PRNGKey(0), sample).get("params")
    # batch divisible by every world size the soak visits
    batch = n0 * (n0 - 1) * 2

    def batch_for(step):
        rng = np.random.RandomState(step)
        x = rng.rand(batch, 28, 28).astype(np.float32)
        y = rng.randint(0, 10, batch)
        return x, y

    def step_builder(world):
        tx = hvd.DistributedOptimizer(optax.adam(1e-3), shard_optimizer=True)
        step = make_shardmap_train_step(
            model, tx, loss_fn=softmax_xent, shard_optimizer=True,
            instrument=False)

        def step_fn(state, i):
            x, y = batch_for(i)
            p, _, os_, loss = step(
                state["params"], {}, state["opt_state"],
                shard_batch(x), shard_batch(y))
            return {"params": p, "opt_state": os_}

        return step_fn

    tx0 = hvd.DistributedOptimizer(optax.adam(1e-3), shard_optimizer=True)
    params = replicate(jax.tree_util.tree_map(jnp.array, params0))
    state = {"params": params, "opt_state": tx0.init(params)}

    iters = max(args.iters, 10)
    fail_at = max(2, iters // 3)
    join_at = max(fail_at + 2, 2 * iters // 3)
    chaos.configure(
        f"rank_fail=1,rank_fail_at_step={fail_at},"
        f"rank_join_at_step={join_at}")
    t0 = time.time()
    try:
        state = elastic.run(
            step_builder, state, num_steps=iters, snapshot_every=1)
    finally:
        chaos.reset()
    wall = time.time() - t0

    hist = hvd.metrics.value("resilience_elastic_resize_seconds") or {}
    count = int(hist.get("count", 0) or 0)
    total = float(hist.get("sum", 0.0) or 0.0)
    latency = total / count if count else None
    if latency is not None and hvd.metrics.enabled():
        hvd.metrics.gauge(
            "elastic_recovery_latency_seconds",
            help="mean wall time of one elastic membership change",
        ).set(latency)
    out = {
        "metric": "elastic_recovery_latency",
        "value": round(latency, 4) if latency is not None else None,
        "unit": "s",
        "n_chips": n0,
        "resizes": count,
        "generations": hvd.metrics.value("resilience_elastic_generation"),
        "soak_wall_s": round(wall, 3),
        "steps": iters,
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(out), flush=True)
    return 0


def _run_benchmark(args):
    from horovod_tpu.run.env_util import install_sigterm_exit

    install_sigterm_exit()  # watchdog SIGTERM -> clean device teardown

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    import horovod_tpu.models as models
    from horovod_tpu.training import (
        init_model,
        make_jit_train_step,
        replicate,
        shard_batch,
    )

    try:
        hvd.init()
    except Exception as e:  # backend died between probe and init
        _emit_skip(f"tpu-unavailable: {type(e).__name__}", args.model)
        return 0
    n_chips = hvd.size()
    model = getattr(models, _MODELS[args.model][0])(num_classes=1000)
    compression, error_feedback, comp_name = _resolve_compression(args)
    # resolve once: the flag OR the env fallback the optimizer itself honors
    # (HOROVOD_SHARD_OPTIMIZER=1 without --shard-optimizer must not clobber
    # the sharded state layout below or misreport the sync mode)
    from horovod_tpu.optim import _env_true

    sharded = bool(args.shard_optimizer) or _env_true("HOROVOD_SHARD_OPTIMIZER")
    tx = hvd.DistributedOptimizer(
        optax.sgd(0.01, momentum=0.9), compression=compression,
        error_feedback=error_feedback, shard_optimizer=sharded,
    )

    rng = jax.random.PRNGKey(0)
    global_batch = args.batch_size * n_chips
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    params, batch_stats = init_model(model, rng, sample)
    params = replicate(params)
    batch_stats = replicate(batch_stats)
    # sharded mode: init already placed the [N, shard] state P(data) —
    # replicate() here would clobber the ZeRO-1 layout
    opt_state = (
        tx.init(params) if sharded else replicate(tx.init(params))
    )

    # instrument=False: the AOT-compiled executable below is wrapped with
    # the measured per-step FLOPs instead (double-wrapping would double
    # count train_steps)
    step = make_jit_train_step(model, tx, instrument=False)

    images_np = np.random.RandomState(0).rand(
        global_batch, args.image_size, args.image_size, 3
    ).astype(np.float32)
    labels_np = np.random.RandomState(1).randint(0, 1000, global_batch)
    images = shard_batch(images_np)
    labels = shard_batch(labels_np)

    # AOT-compile once and run the loop through the compiled executable: the
    # same compile serves execution and cost analysis (a separate
    # lower().compile() would not populate jit's dispatch cache and would
    # compile ResNet-50 twice)
    step_flops = None
    try:
        compiled = step.lower(
            params, batch_stats, opt_state, images, labels
        ).compile()
        step = compiled
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        step_flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        pass  # cost analysis is best-effort; MFU line is skipped without it
    # feed the metrics registry too (train_steps / train_step_seconds /
    # train_mfu): the benchmark exercises the same observability surface a
    # real training job gets, and the summary rides stderr for debugging
    from horovod_tpu.training import instrument_step

    step = instrument_step(step, batch_arg=3, flops_per_step=step_flops)

    for _ in range(args.warmup):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    jax.block_until_ready((params, loss))

    from horovod_tpu.profiler import timed_steps

    state = [params, batch_stats, opt_state]

    def run_one():
        state[0], state[1], state[2], loss = step(
            state[0], state[1], state[2], images, labels
        )
        return loss

    losses, dt = timed_steps(run_one, args.iters)
    assert all(np.isfinite(l) for l in losses), f"non-finite loss: {losses[-5:]}"

    img_per_sec = global_batch * args.iters / dt
    per_chip = img_per_sec / n_chips

    device_kind = jax.devices()[0].device_kind
    result = {
        "metric": f"{args.model}_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": (
            round(per_chip / BASELINE_IMG_S_PER_CHIP, 3)
            if _MODELS[args.model][2] else None
        ),
        "n_chips": n_chips,
        "device_kind": device_kind,
    }
    sync_mode = "sharded" if sharded else "allreduce"
    sync_bytes = hvd.metrics.value("grad_sync_bytes_per_step", mode=sync_mode)
    if sync_bytes is not None:
        result["grad_sync_mode"] = sync_mode
        result["grad_sync_bytes_per_step"] = sync_bytes
    if comp_name != "none":
        result["compression"] = comp_name
    from horovod_tpu.profiler import device_peak_flops

    peak = device_peak_flops(device_kind)
    if step_flops is not None and peak is not None:
        achieved = step_flops * args.iters / dt
        result["mfu"] = round(achieved / (n_chips * peak), 4)
        result["model_tflops_per_step"] = round(step_flops / 1e12, 3)
    # The headline measurement is complete HERE — print it before the
    # optional trace capture so a wedge during the traced steps can never
    # destroy it (the parent parses the LAST JSON line, and run_rung
    # recovers flushed partial stdout even from a watchdog-killed child).
    print(json.dumps(result), flush=True)
    print("metrics snapshot:\n" + hvd.metrics.summary(),
          file=sys.stderr, flush=True)
    if args.trace_dir:
        # after the timed loop so tracing overhead never pollutes img/s;
        # the real-workload overlap artifact (reference docs/timeline.rst)
        try:
            from horovod_tpu.profiler import timeline

            with timeline(args.trace_dir):
                for _ in range(3):
                    run_one()
            jax.block_until_ready(state[0])
            result["trace_dir"] = args.trace_dir
        except Exception as e:  # trace is best-effort evidence
            result["trace_error"] = f"{type(e).__name__}: {e}"[:200]
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    sys.exit(main())
