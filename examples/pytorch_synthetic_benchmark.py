#!/usr/bin/env python
"""Synthetic benchmark for the torch frontend — analog of reference
``examples/pytorch_synthetic_benchmark.py`` (img/s with allreduced grads).
The model is a small conv net (torch runs on host CPU here; the flagship
TPU benchmark is the JAX ``bench.py`` at the repo root)."""

import argparse
import time

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-warmup", type=int, default=3)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()
    model = torch.nn.Sequential(
        torch.nn.Conv2d(3, 32, 3, stride=2), torch.nn.ReLU(),
        torch.nn.Conv2d(32, 64, 3, stride=2), torch.nn.ReLU(),
        torch.nn.AdaptiveAvgPool2d(1), torch.nn.Flatten(),
        torch.nn.Linear(64, 1000),
    )
    compression = (
        hvd.Compression.fp16 if args.fp16_allreduce else hvd.Compression.none
    )
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01),
        named_parameters=model.named_parameters(),
        compression=compression,
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, 64, 64)
    target = torch.randint(0, 1000, (args.batch_size,))

    def step():
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()

    for _ in range(args.num_warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        step()
    dt = time.perf_counter() - t0
    img_sec = args.batch_size * args.num_iters / dt
    total = hvd.size() * img_sec
    if hvd.rank() == 0:
        print(f"Img/sec per rank: {img_sec:.1f}")
        print(f"Total img/sec on {hvd.size()} rank(s): {total:.1f}")


if __name__ == "__main__":
    main()
