"""Native control-plane microbenchmark: steady-state negotiation throughput.

Measures the C++ core's async named-tensor path (the reference's background
loop: enqueue -> negotiate -> fused launch -> handle completion) in
steps/sec for a synthetic N-tensor "model", under:

- cache ON  (steady state rides the response-cache bitvector sync)
- cache OFF (every step renegotiates by name list)
- fusion ON vs OFF (threshold 0 -> one response per tensor)

Run: PYTHONPATH=. python examples/core_microbench.py [--tensors 16]
"""

import argparse
import os
import time


def run_config(label, n_tensors, elems, steps, cache, fusion_threshold):
    os.environ["HOROVOD_CYCLE_TIME"] = "1"
    os.environ["HOROVOD_CACHE_CAPACITY"] = "1024" if cache else "0"
    os.environ["HOROVOD_FUSION_THRESHOLD"] = str(fusion_threshold)
    import numpy as np

    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE

    core = NativeCore(rank=0, size=1)
    if not cache:
        core.set_cache_enabled(False)
    x = np.ones((elems,), np.float32)
    try:
        # warmup: populate caches + compile the grouped XLA programs
        for _ in range(3):
            hs = [
                core.enqueue(f"g{i}", x, REQUEST_ALLREDUCE, op=1)
                for i in range(n_tensors)
            ]
            for h in hs:
                h.wait(timeout=60)
        t0 = time.perf_counter()
        for _ in range(steps):
            hs = [
                core.enqueue(f"g{i}", x, REQUEST_ALLREDUCE, op=1)
                for i in range(n_tensors)
            ]
            for h in hs:
                h.wait(timeout=60)
        dt = time.perf_counter() - t0
    finally:
        core.shutdown()
    sps = steps / dt
    print(
        f"{label:30s}: {sps:7.1f} steps/s "
        f"({sps * n_tensors:8.1f} tensors/s)"
    )
    return sps


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tensors", type=int, default=16)
    p.add_argument("--elems", type=int, default=1024)
    p.add_argument("--steps", type=int, default=50)
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()

    base = run_config(
        "cache on, fusion 64MB", args.tensors, args.elems, args.steps,
        cache=True, fusion_threshold=64 * 1024 * 1024,
    )
    no_fuse = run_config(
        "cache on, fusion off", args.tensors, args.elems, args.steps,
        cache=True, fusion_threshold=0,
    )
    no_cache = run_config(
        "cache off, fusion 64MB", args.tensors, args.elems, args.steps,
        cache=False, fusion_threshold=64 * 1024 * 1024,
    )
    print(
        f"fusion speedup {base / no_fuse:.2f}x, "
        f"cache speedup {base / no_cache:.2f}x "
        f"({args.tensors} tensors/step)"
    )
    hvd.shutdown()


if __name__ == "__main__":
    main()
