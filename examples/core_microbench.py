"""Native control-plane microbenchmark: steady-state negotiation throughput.

Measures the C++ core's async named-tensor path (the reference's background
loop: enqueue -> negotiate -> fused launch -> handle completion) in
steps/sec for a synthetic N-tensor "model", under:

- cache ON  (steady state rides the response-cache bitvector sync)
- cache OFF (every step renegotiates by name list)
- fusion ON vs OFF (threshold 0 -> one response per tensor)

Run: PYTHONPATH=. python examples/core_microbench.py [--tensors 16]

``--np 2`` times the same steady state CROSS-PROCESS through the launcher
(real TCP negotiation + cross-process XLA data plane), cache on vs off via
``HOROVOD_CACHE_CAPACITY``. Honest expectation: at 2 localhost ranks the
data-plane launch dominates and the cache moves end-to-end throughput
~0% — the bitvector sync exists to replace a coordinator gather that
scales with ranks x names, which only shows at large rank counts. This
mode is the harness for measuring that when real multi-host is available.
"""

import argparse
import os
import time


def _bench_loop(core, n_tensors, elems, steps, timeout=120):
    """Warmup (3 iterations: populate caches, compile the grouped XLA
    programs) then the timed steady-state loop; returns steps/sec."""
    import numpy as np

    from horovod_tpu.core import REQUEST_ALLREDUCE

    x = np.ones((elems,), np.float32)
    for _ in range(3):
        hs = [core.enqueue(f"g{i}", x, REQUEST_ALLREDUCE, op=1)
              for i in range(n_tensors)]
        for h in hs:
            h.wait(timeout=timeout)
    t0 = time.perf_counter()
    for _ in range(steps):
        hs = [core.enqueue(f"g{i}", x, REQUEST_ALLREDUCE, op=1)
              for i in range(n_tensors)]
        for h in hs:
            h.wait(timeout=timeout)
    return steps / (time.perf_counter() - t0)


def run_config(label, n_tensors, elems, steps, cache, fusion_threshold):
    os.environ["HOROVOD_CYCLE_TIME"] = "1"
    os.environ["HOROVOD_CACHE_CAPACITY"] = "1024" if cache else "0"
    os.environ["HOROVOD_FUSION_THRESHOLD"] = str(fusion_threshold)
    from horovod_tpu.core import NativeCore

    core = NativeCore(rank=0, size=1)
    if not cache:
        core.set_cache_enabled(False)
    try:
        sps = _bench_loop(core, n_tensors, elems, steps, timeout=60)
    finally:
        core.shutdown()
    print(
        f"{label:30s}: {sps:7.1f} steps/s "
        f"({sps * n_tensors:8.1f} tensors/s)"
    )
    return sps


def _two_proc_sweep(n_tensors, elems, steps):
    """Worker body for --np 2: one timed config over a real TCP controller
    (cache on/off is decided by HOROVOD_CACHE_CAPACITY in the job env —
    toggling at runtime is deliberately rejected in multi-process)."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import basics

    hvd.init()
    core = basics.core()
    assert core is not None, "launch with use_native_core"
    return {"rank": hvd.process_rank(),
            "sps": _bench_loop(core, n_tensors, elems, steps)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tensors", type=int, default=16)
    p.add_argument("--elems", type=int, default=1024)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--np", type=int, default=1, dest="nproc",
                   help="2: cross-process sweep through the launcher")
    args = p.parse_args()

    if args.nproc > 1:
        import functools

        from horovod_tpu.run import runner

        results = {}
        for label, capacity in (("cache_on", "1024"), ("cache_off", "0")):
            env = dict(os.environ)
            env["HOROVOD_CYCLE_TIME"] = "1"
            env["HOROVOD_CACHE_CAPACITY"] = capacity
            out = runner.run(
                functools.partial(
                    _two_proc_sweep, args.tensors, args.elems, args.steps),
                np=args.nproc, env=env, use_native_core=True, timeout_s=600,
            )
            results[label] = out[0]["sps"]
            print(f"{args.nproc}-process {label:10s}: {out[0]['sps']:7.1f} "
                  f"steps/s ({out[0]['sps'] * args.tensors:8.1f} tensors/s)")
        print(f"cross-process cache speedup "
              f"{results['cache_on'] / results['cache_off']:.2f}x")
        return

    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()

    base = run_config(
        "cache on, fusion 64MB", args.tensors, args.elems, args.steps,
        cache=True, fusion_threshold=64 * 1024 * 1024,
    )
    no_fuse = run_config(
        "cache on, fusion off", args.tensors, args.elems, args.steps,
        cache=True, fusion_threshold=0,
    )
    no_cache = run_config(
        "cache off, fusion 64MB", args.tensors, args.elems, args.steps,
        cache=False, fusion_threshold=64 * 1024 * 1024,
    )
    print(
        f"fusion speedup {base / no_fuse:.2f}x, "
        f"cache speedup {base / no_cache:.2f}x "
        f"({args.tensors} tensors/step)"
    )
    hvd.shutdown()


if __name__ == "__main__":
    main()
