"""Synthetic causal-LM training benchmark: tokens/s/chip + MFU.

The image families' analog lives in ``bench.py``; this harness gives the
transformer stack (the long-context/TPU-native side of the framework) the
same hardware perf story: one DP train step over all visible chips, bf16
compute, optional flash attention (Pallas) and GQA, cost-analysis-derived
MFU. Prints ONE JSON line, same shape as ``bench.py``'s.

    python examples/transformer_lm_benchmark.py --dim 2048 --depth 16

On CPU for a smoke run:

    JAX_PLATFORMS=cpu python examples/transformer_lm_benchmark.py \
        --dim 64 --depth 2 --heads 4 --seq-len 128 --batch 2 --steps 3
"""

import argparse
import json
import os
import sys
import time

# self-sufficient from any cwd (`python examples/transformer_lm_benchmark.py`
# puts examples/ on sys.path[0], not the repo root)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.run.env_util import install_sigterm_exit

install_sigterm_exit()  # watchdog SIGTERM -> clean device teardown

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.models import TransformerLM
from horovod_tpu.training import make_jit_train_step, replicate, shard_batch


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=8, help="per-chip batch")
    p.add_argument("--dim", type=int, default=2048)
    p.add_argument("--depth", type=int, default=16)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="GQA key/value heads (default: same as --heads)")
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--flash", action="store_true",
                   help="use the Pallas flash-attention kernel")
    p.add_argument("--rope", action="store_true",
                   help="rotary position embeddings instead of a learned table")
    p.add_argument("--mode", choices=["train", "decode"], default="train",
                   help="train: tokens/s/chip + MFU of a DP train step; "
                        "decode: kv-cache generation tokens/s/chip")
    p.add_argument("--prompt-len", type=int, default=512,
                   help="decode mode: prefill length")
    args = p.parse_args()
    if args.steps < 1 or args.warmup < 1 or args.batch < 1:
        p.error("--steps, --warmup and --batch must be >= 1")
    if args.mode == "decode":
        if args.flash:
            p.error("--flash has no effect in decode mode: the kv-cache "
                    "path uses its own single-step attention")
        if args.prompt_len < 1 or args.seq_len <= args.prompt_len:
            p.error("decode mode needs 1 <= --prompt-len < --seq-len")

    hvd.init()
    n_chips = hvd.size()

    attention_fn = None
    if args.flash:
        from horovod_tpu.ops.flash_attention import flash_attention

        attention_fn = flash_attention
    model_kwargs = dict(
        vocab=args.vocab, dim=args.dim, depth=args.depth, heads=args.heads,
        kv_heads=args.kv_heads, max_len=args.seq_len,
        pos_embedding="rope" if args.rope else "learned",
    )
    if attention_fn is not None:
        model_kwargs["attention_fn"] = attention_fn
    model = TransformerLM(**model_kwargs)

    if args.mode == "decode":
        return _run_decode(args, model)

    rng = np.random.RandomState(0)
    global_batch = args.batch * n_chips
    tokens_np = rng.randint(
        0, args.vocab, (global_batch, args.seq_len)).astype(np.int32)
    tokens = shard_batch(tokens_np)
    targets = shard_batch(np.roll(tokens_np, -1, axis=1))

    params = model.init(
        jax.random.PRNGKey(0), jnp.asarray(tokens_np[:1]))["params"]
    tx = hvd.DistributedOptimizer(optax.adamw(1e-4))
    opt_state = replicate(tx.init(params))
    params = replicate(params)

    def lm_xent(logits, tgts):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        ll = jnp.take_along_axis(logp, tgts[..., None], axis=-1)
        return -jnp.mean(ll)

    step = make_jit_train_step(model, tx, loss_fn=lm_xent)
    batch_stats = {}  # TransformerLM is stateless

    step_flops = None
    try:
        compiled = step.lower(
            params, batch_stats, opt_state, tokens, targets).compile()
        step = compiled
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        step_flops = float(ca.get("flops", 0.0)) or None
    except Exception as e:
        print(f"cost analysis unavailable: {e}", file=sys.stderr)

    for _ in range(args.warmup):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, tokens, targets)
    jax.block_until_ready((params, loss))

    from horovod_tpu.profiler import timed_steps

    state = [params, batch_stats, opt_state]

    def run_one():
        state[0], state[1], state[2], loss = step(
            state[0], state[1], state[2], tokens, targets)
        return loss

    losses, dt = timed_steps(run_one, args.steps)
    assert all(np.isfinite(l) for l in losses), f"non-finite: {losses[-3:]}"

    tokens_per_sec = global_batch * args.seq_len * args.steps / dt
    device_kind = jax.devices()[0].device_kind
    result = {
        "metric": "transformer_lm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec / n_chips, 1),
        "unit": "tokens/s/chip",
        "n_chips": n_chips,
        "device_kind": device_kind,
        "flash": bool(args.flash),
        "rope": bool(args.rope),
    }
    from horovod_tpu.profiler import device_peak_flops

    peak = device_peak_flops(device_kind)
    if step_flops is not None and peak is not None:
        achieved = step_flops * args.steps / dt
        result["mfu"] = round(achieved / (n_chips * peak), 4)
        result["model_tflops_per_step"] = round(step_flops / 1e12, 3)
    print(json.dumps(result))


def _run_decode(args, model):
    """KV-cache generation throughput: warm generate() calls compile the
    prefill + scan, then timed runs. Single-process (decode is per-replica;
    DP replicates it)."""
    from horovod_tpu.models import generate

    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(
        0, args.vocab, (args.batch, args.prompt_len)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), prompt[:, :8])["params"]

    new_tokens = args.seq_len - args.prompt_len

    # generate() is pure -> jit the whole prefill + scan once
    gen = jax.jit(lambda p, pr: generate(
        model, p, pr, max_new_tokens=new_tokens))
    for _ in range(args.warmup):
        out = gen(params, prompt)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = gen(params, prompt)
        _ = int(np.asarray(out[0, -1]))  # host fence
    dt = time.perf_counter() - t0

    result = {
        "metric": "transformer_lm_decode_tokens_per_sec",
        "value": round(args.batch * new_tokens * args.steps / dt, 1),
        "unit": "tokens/s",
        "batch": args.batch,
        "prompt_len": args.prompt_len,
        "new_tokens": new_tokens,
        "device_kind": jax.devices()[0].device_kind,
        "rope": bool(args.rope),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
