#!/usr/bin/env python
"""Synthetic benchmark for the TF2 frontend — the rebuild's analog of the
reference's flagship benchmark (``examples/tensorflow2_synthetic_benchmark.py``,
BASELINE config 2): Keras application model, synthetic images,
``DistributedGradientTape`` + optional fp16 compression, img/s per iter.

The TF2 path exercises the frontend end-to-end (gradient tape wrapping,
compression, broadcast_variables); the flagship TPU number comes from the JAX
``bench.py`` at the repo root, which drives the same collective layer from a
jitted XLA training step.
"""

import argparse
import time

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def build_model(name: str):
    if name == "tiny":
        # smoke-test model: same topology class (conv -> pool -> dense)
        return tf.keras.Sequential([
            tf.keras.layers.Conv2D(16, 3, strides=2, activation="relu"),
            tf.keras.layers.Conv2D(32, 3, strides=2, activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(10),
        ])
    return getattr(tf.keras.applications, name)(weights=None)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="ResNet50",
                   help="tf.keras.applications model name, or 'tiny'")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup-batches", type=int, default=2)
    p.add_argument("--num-batches-per-iter", type=int, default=3)
    p.add_argument("--num-iters", type=int, default=3)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()
    model = build_model(args.model)
    opt = tf.optimizers.SGD(0.01)
    compression = (
        hvd.Compression.fp16 if args.fp16_allreduce else hvd.Compression.none
    )

    size = args.image_size if args.model != "tiny" else 32
    data = tf.random.uniform([args.batch_size, size, size, 3])
    target = tf.random.uniform(
        [args.batch_size], minval=0, maxval=10, dtype=tf.int64
    )

    def benchmark_step():
        with tf.GradientTape() as tape:
            probs = model(data, training=True)
            loss = tf.losses.sparse_categorical_crossentropy(
                target, probs, from_logits=True
            )
        tape = hvd.DistributedGradientTape(tape, compression=compression)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

    # warmup (builds variables), then sync initial state across ranks
    for _ in range(args.num_warmup_batches):
        benchmark_step()
    hvd.broadcast_variables(model.variables, root_rank=0)
    # Keras 3 made optimizer.variables a property; Keras 2 had a method
    opt_vars = opt.variables() if callable(opt.variables) else opt.variables
    hvd.broadcast_variables(opt_vars, root_rank=0)

    if hvd.rank() == 0:
        print(f"Model: {args.model}")
        print(f"Batch size: {args.batch_size}")
        print(f"Number of workers: {hvd.size()}")

    img_secs = []
    for x in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        dt = time.perf_counter() - t0
        img_sec = args.batch_size * args.num_batches_per_iter / dt
        if hvd.rank() == 0:
            print(f"Iter #{x}: {img_sec:.1f} img/sec per worker")
        img_secs.append(img_sec)

    img_sec_mean = np.mean(img_secs)
    img_sec_conf = 1.96 * np.std(img_secs)
    if hvd.rank() == 0:
        print(f"Img/sec per worker: {img_sec_mean:.1f} +-{img_sec_conf:.1f}")
        print(
            f"Total img/sec on {hvd.size()} worker(s): "
            f"{hvd.size() * img_sec_mean:.1f} +-{hvd.size() * img_sec_conf:.1f}"
        )


if __name__ == "__main__":
    main()
