"""Per-collective bridge overhead: dlpack (zero-copy) vs forced host copy.

The reference's TF kernels operate in-graph on device buffers
(``horovod/tensorflow/mpi_ops.cc:286-473``), so its per-collective frontend
overhead is one enqueue. This rebuild crosses the TF<->JAX boundary instead;
eager tensors ride the dlpack protocol (shared buffer, no copy). This
microbench measures that boundary in isolation — same collective, same mesh,
bridge path toggled — and prints µs/op for both.

Run: PYTHONPATH=. python examples/tensorflow2_dlpack_microbench.py
"""

import argparse
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--size-mb", type=float, default=4.0)
    p.add_argument("--iters", type=int, default=50)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import tensorflow as tf

    import horovod_tpu as hvd
    from horovod_tpu.tensorflow import mpi_ops

    hvd.init()
    n_elem = int(args.size_mb * 1024 * 1024 / 4)
    t = tf.constant(np.random.RandomState(0).rand(n_elem).astype(np.float32))

    def timed(label, fn):
        fn()  # warmup/compile
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn()
        np.asarray(out)  # fence
        us = (time.perf_counter() - t0) / args.iters * 1e6
        print(f"{label}: {us:,.0f} us/op")
        return us

    dlpack_us = timed(
        "allreduce via dlpack bridge",
        lambda: mpi_ops.allreduce(t, mpi_ops.Sum),
    )

    # same collective with the bridge forced through host numpy
    def copy_path():
        a = jnp.asarray(np.asarray(t))
        out = hvd.allreduce(a, hvd.Sum)
        return tf.convert_to_tensor(np.asarray(out))

    copy_us = timed("allreduce via host-copy bridge", copy_path)

    # boundary-only cost (no collective): dlpack round trip vs numpy round trip
    rt_dlpack = timed(
        "tf->jax->tf dlpack round trip",
        lambda: mpi_ops._jax_to_tf(mpi_ops._tf_to_jax(t)),
    )
    rt_copy = timed(
        "tf->jax->tf host-copy round trip",
        lambda: tf.convert_to_tensor(np.asarray(jnp.asarray(np.asarray(t)))),
    )
    print(
        f"bridge speedup: {copy_us / max(dlpack_us, 1e-9):.2f}x end-to-end, "
        f"{rt_copy / max(rt_dlpack, 1e-9):.2f}x boundary-only "
        f"({args.size_mb} MB tensor)"
    )


if __name__ == "__main__":
    main()
