#!/usr/bin/env python
"""Pipeline-parallel training over the ``pipe`` mesh axis.

TPU-native capability beyond the reference (Horovod 0.19.2 is
data-parallel only — SURVEY.md §2.7): a residual-MLP block stack is split
into stages sharded over the pipe axis, microbatches stream through a
GPipe or interleaved (circular) schedule, and the whole step — schedule,
backward, optimizer — is one jitted program built by
``make_pp_train_step``.

    python examples/jax_pipeline_transformer.py --schedule interleaved

(CPU experimentation: XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu.)
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.parallel import (
    PIPELINE_AXIS,
    make_interleaved_stage_params,
    make_stage_params,
)
from horovod_tpu.training import make_pp_train_step


def stage_fn(params, h):
    """One stage: residual MLP block (pre-norm, GELU)."""
    w1, b1, w2, b2 = params
    x = h - jnp.mean(h, axis=-1, keepdims=True)
    x = x / jnp.sqrt(jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)
    return h + jax.nn.gelu(x @ w1 + b1) @ w2 + b2


def make_stage(rng, d, hid):
    return (
        jnp.asarray(rng.randn(d, hid).astype(np.float32) * 0.1),
        jnp.zeros((hid,), jnp.float32),
        jnp.asarray(rng.randn(hid, d).astype(np.float32) * 0.1),
        jnp.zeros((d,), jnp.float32),
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--micro-batch", type=int, default=8)
    p.add_argument("--n-micro", type=int, default=8)
    p.add_argument("--virtual", type=int, default=2,
                   help="stages per device for the interleaved schedule")
    p.add_argument("--schedule", choices=["gpipe", "interleaved"],
                   default="interleaved")
    p.add_argument("--steps", type=int, default=20)
    args = p.parse_args()

    n = len(jax.devices())
    hvd.init(axes={PIPELINE_AXIS: n})
    interleaved = args.schedule == "interleaved"
    v = args.virtual if interleaved else 1
    L = n * v
    print(f"pipe={n} schedule={args.schedule} stages={L} "
          f"micro={args.n_micro}x{args.micro_batch}")

    rng = np.random.RandomState(0)
    stages = [make_stage(rng, args.dim, args.hidden) for _ in range(L)]
    stacked = (
        make_interleaved_stage_params(stages, n)
        if interleaved else make_stage_params(stages)
    )
    tx = optax.adam(1e-3)
    opt_state = jax.vmap(tx.init)(stacked)

    Wt = rng.randn(args.dim, args.dim).astype(np.float32)
    x = jnp.asarray(
        rng.randn(args.n_micro, args.micro_batch, args.dim).astype(np.float32)
    )
    y = jnp.tanh(x @ Wt)

    step = make_pp_train_step(stage_fn, tx, interleaved=interleaved)
    stacked, opt_state, loss = step(stacked, opt_state, x, y)  # compile
    t0 = time.perf_counter()
    for i in range(args.steps):
        stacked, opt_state, loss = step(stacked, opt_state, x, y)
        if i % 5 == 0:
            print(f"step {i}: loss={float(loss):.4f}")
    dt = (time.perf_counter() - t0) / args.steps
    print(f"final loss={float(loss):.4f}, {dt * 1e3:.1f} ms/step")


if __name__ == "__main__":
    main()
