#!/usr/bin/env python
"""MNIST-style training with the torch frontend — analog of reference
``examples/pytorch_mnist.py``: DistributedOptimizer + broadcast of params and
optimizer state, per-rank data sharding, metric allreduce at epoch end.

Single host:   python examples/pytorch_mnist.py
Multi-process: python -m horovod_tpu.run -np 2 -- python examples/pytorch_mnist.py
"""

import argparse

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, 5)
        self.conv2 = torch.nn.Conv2d(10, 20, 5)
        self.bn = hvd.SyncBatchNorm(20)
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.bn(self.conv2(x)), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return self.fc2(x)


def load_data():
    rng = np.random.RandomState(0)
    x = rng.rand(2048, 1, 28, 28).astype(np.float32)
    teacher = rng.randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(len(x), -1) @ teacher).argmax(1)
    return torch.from_numpy(x), torch.from_numpy(y)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    args = p.parse_args()

    hvd.init()
    x, y = load_data()
    # per-rank shard (reference: DistributedSampler)
    n = len(x) // hvd.process_size()
    r = hvd.process_rank()
    x, y = x[r * n:(r + 1) * n], y[r * n:(r + 1) * n]

    model = Net()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(),
                        lr=args.lr * hvd.size(), momentum=0.5),
        named_parameters=model.named_parameters(),
        compression=hvd.Compression.fp16,
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    for epoch in range(args.epochs):
        model.train()
        losses = []
        for i in range(0, len(x), args.batch_size):
            bx, by = x[i:i + args.batch_size], y[i:i + args.batch_size]
            opt.zero_grad()
            loss = F.cross_entropy(model(bx), by)
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        # epoch metric averaged over ranks (reference MetricAverageCallback)
        avg = float(hvd.allreduce(torch.tensor(np.mean(losses))))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={avg:.4f}")


if __name__ == "__main__":
    main()
