"""Keras MNIST with horovod_tpu — the rebuild's analog of reference
``examples/tensorflow2_keras_mnist.py``: DistributedOptimizer with LR scaled
by size, broadcast + metric-average + warmup callbacks, rank-0-only
checkpointing."""

import argparse

import keras
import numpy as np

import horovod_tpu.keras as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--synthetic", action="store_true",
                   help="random data instead of downloading MNIST")
    args = p.parse_args()

    hvd.init()

    if args.synthetic:
        x = np.random.rand(2048, 28, 28, 1).astype("float32")
        y = np.random.randint(0, 10, 2048)
    else:
        (x, y), _ = keras.datasets.mnist.load_data()
        x = (x / 255.0).astype("float32")[..., None]

    # shard the dataset by rank (each process sees 1/size of the data)
    x = x[hvd.rank()::hvd.size()]
    y = y[hvd.rank()::hvd.size()]

    model = keras.Sequential([
        keras.layers.Input((28, 28, 1)),
        keras.layers.Conv2D(32, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Conv2D(64, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])

    # scale LR by number of workers (reference examples/tensorflow2_keras_mnist.py)
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.01 * hvd.size(), momentum=0.9)
    )
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    callbacks = [
        hvd.BroadcastGlobalVariablesCallback(0),
        hvd.MetricAverageCallback(),
        hvd.LearningRateWarmupCallback(warmup_epochs=1, verbose=1),
    ]
    if hvd.rank() == 0:
        callbacks.append(
            keras.callbacks.ModelCheckpoint("./checkpoint-{epoch}.keras")
        )

    model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
              callbacks=callbacks, verbose=1 if hvd.rank() == 0 else 0)


if __name__ == "__main__":
    main()
