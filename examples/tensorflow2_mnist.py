"""TF2 custom-training-loop MNIST — the rebuild's analog of reference
``examples/tensorflow2_mnist.py``: DistributedGradientTape, broadcast of
variables after the first step, LR scaled by size, rank-0 checkpointing."""

import argparse

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--synthetic", action="store_true")
    args = p.parse_args()

    hvd.init()

    if args.synthetic:
        x = np.random.rand(4096, 28, 28, 1).astype("float32")
        y = np.random.randint(0, 10, 4096).astype("int64")
    else:
        (x, y), _ = tf.keras.datasets.mnist.load_data()
        x = (x / 255.0).astype("float32")[..., None]
        y = y.astype("int64")

    dataset = (
        tf.data.Dataset.from_tensor_slices((x, y))
        .shard(hvd.size(), hvd.rank())
        .repeat().shuffle(10000).batch(args.batch_size)
    )

    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(32, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    loss_obj = tf.losses.SparseCategoricalCrossentropy(from_logits=True)
    opt = tf.optimizers.SGD(0.01 * hvd.size(), momentum=0.9)
    checkpoint = tf.train.Checkpoint(model=model)

    for step, (images, labels) in enumerate(dataset.take(args.steps)):
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            probs = model(images, training=True)
            loss = loss_obj(labels, probs)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))

        if step == 0:
            # sync initial state after the first gradient step, so optimizer
            # slots exist (reference tensorflow2_mnist.py comment)
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)

        if step % 50 == 0 and hvd.rank() == 0:
            print(f"step {step}\tloss {float(loss):.4f}")

    if hvd.rank() == 0:
        checkpoint.save("./tf2_mnist_ckpt")


if __name__ == "__main__":
    main()
