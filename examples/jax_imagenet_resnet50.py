"""ImageNet-style ResNet-50 training — the rebuild's flagship end-to-end
example (reference ``examples/pytorch_imagenet_resnet50.py`` /
``keras_imagenet_resnet50.py``), strung through the framework's full
surface: sharded prefetching input pipeline, DP train step with the
distributed optimizer, LR warmup + stepwise decay (the reference's
schedule: warmup over 5 epochs from lr/size, /10 at epochs 30/60/80),
rank-0 async checkpointing with resume, and optional Adasum / fp16
gradient compression / error feedback.

Runs on synthetic data by default (same shapes as ImageNet) so it works
anywhere; point ``--data-dir`` at ``.npy`` files (``images.npy`` NHWC
uint8/float32, ``labels.npy`` int) for real data.

    python examples/jax_imagenet_resnet50.py --epochs 1 --limit-steps 50

CPU smoke: JAX_PLATFORMS=cpu with --image-size 32 --batch-size 8.
"""

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import horovod_tpu as hvd
from horovod_tpu.checkpoint import CheckpointManager
from horovod_tpu.compression import Compression
from horovod_tpu.data import ShardedLoader
import horovod_tpu.models as models
from horovod_tpu.training import init_model, make_jit_train_step, replicate


def lr_schedule(base_lr: float, size: int, steps_per_epoch: int):
    """Reference schedule (pytorch_imagenet_resnet50.py:18-24 flags): ramp
    from base_lr to base_lr*size over 5 warmup epochs, then /10 at epochs
    30/60/80 — expressed as one optax schedule so it lives inside jit."""
    warmup = optax.linear_schedule(
        base_lr, base_lr * size, 5 * steps_per_epoch
    )
    decay = optax.piecewise_constant_schedule(
        base_lr * size,
        {25 * steps_per_epoch: 0.1,   # counted from end of warmup
         55 * steps_per_epoch: 0.1,
         75 * steps_per_epoch: 0.1},
    )
    return optax.join_schedules([warmup, decay], [5 * steps_per_epoch])


def load_data(args):
    if args.data_dir:
        import os

        images = np.load(os.path.join(args.data_dir, "images.npy"),
                         mmap_mode="r")
        labels = np.load(os.path.join(args.data_dir, "labels.npy"))
        return images, labels, int(labels.max()) + 1
    rng = np.random.RandomState(0)
    n = args.synthetic_examples
    images = rng.rand(
        n, args.image_size, args.image_size, 3).astype(np.float32)
    labels = rng.randint(0, 1000, n)
    return images, labels, 1000


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", default=None,
                   help="directory with images.npy / labels.npy "
                        "(default: synthetic)")
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-chip batch (reference default 32/GPU)")
    p.add_argument("--base-lr", type=float, default=0.0125,
                   help="single-chip LR; scaled by hvd.size() after warmup")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--synthetic-examples", type=int, default=1024)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=200,
                   help="steps between async checkpoints")
    p.add_argument("--limit-steps", type=int, default=0,
                   help="stop after N total steps (0 = run the epochs out)")
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet18", "resnet34", "resnet50", "resnet101",
                            "resnet152"],
                   help="ResNet depth (tf_cnn_benchmarks-style selector)")
    p.add_argument("--adasum", action="store_true")
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--error-feedback", action="store_true",
                   help="EF-SGD residual for --fp16-allreduce")
    args = p.parse_args()

    hvd.init()
    images, labels, num_classes = load_data(args)
    global_batch = args.batch_size * hvd.size()
    loader = ShardedLoader((images, labels), global_batch, seed=1)
    steps_per_epoch = len(loader)
    if steps_per_epoch == 0:
        raise SystemExit("dataset smaller than one global batch")

    compression = Compression.fp16 if args.fp16_allreduce else Compression.none
    sched = lr_schedule(args.base_lr, hvd.size(), steps_per_epoch)
    tx = hvd.DistributedOptimizer(
        optax.sgd(sched, momentum=0.9),
        op=hvd.Adasum if args.adasum else hvd.Average,
        compression=compression,
        error_feedback=args.error_feedback,
    )

    model = getattr(models, args.arch.replace("resnet", "ResNet"))(
        num_classes=num_classes)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    params, batch_stats = init_model(model, jax.random.PRNGKey(0), sample)
    params = replicate(params)
    batch_stats = replicate(batch_stats)
    opt_state = replicate(tx.init(params))
    step_fn = make_jit_train_step(model, tx)

    # optimizer-shape config rides the checkpoint: restoring an opt_state
    # into a differently-flagged optimizer fails deep inside optax — catch
    # it here with an actionable message instead
    opt_config = {"arch": args.arch, "adasum": args.adasum,
                  "fp16": args.fp16_allreduce,
                  "error_feedback": args.error_feedback}
    mgr = None
    start_epoch, global_step = 0, 0
    if args.checkpoint_dir:
        mgr = CheckpointManager(args.checkpoint_dir, max_to_keep=3)
        latest = mgr.latest_step()
        if latest is not None:
            state = mgr.restore(latest)
            if state.get("opt_config", opt_config) != opt_config:
                raise SystemExit(
                    f"checkpoint was written with optimizer flags "
                    f"{state['opt_config']} but this run uses {opt_config}; "
                    f"resume with the same flags (the optimizer state's "
                    f"structure depends on them)"
                )
            params, batch_stats = state["params"], state["batch_stats"]
            opt_state, global_step = state["opt_state"], state["step"]
            # resume point derives solely from global_step; the stored
            # "epoch" is informational only. Using it directly replays a
            # full epoch when the save landed exactly on an epoch boundary
            # (step % steps_per_epoch == 0 -> skip 0 with the old epoch).
            start_epoch = global_step // steps_per_epoch
            if hvd.process_rank() == 0:
                print(f"resumed from step {global_step} "
                      f"(epoch {start_epoch})")

    # a resumed run that already met the limit must not train further
    done = bool(args.limit_steps and global_step >= args.limit_steps)
    # mid-epoch resume: fast-forward past the batches this epoch already
    # consumed, so no data replays and the step-indexed LR schedule stays
    # aligned with data actually seen
    skip = global_step % steps_per_epoch
    epoch, loss, last_saved = start_epoch, None, None
    for epoch in range(start_epoch, args.epochs):
        if done:
            break
        loader.set_epoch(epoch)
        t0, seen = time.perf_counter(), 0
        for b, (x, y) in enumerate(loader):
            if b < skip:
                continue
            params, batch_stats, opt_state, loss = step_fn(
                params, batch_stats, opt_state, x, y)
            global_step += 1
            seen += global_batch
            if mgr and global_step % args.checkpoint_every == 0:
                mgr.save(global_step, {
                    "params": params, "batch_stats": batch_stats,
                    "opt_state": opt_state, "step": global_step,
                    "epoch": epoch, "opt_config": opt_config,
                }, asynchronous=True)
                last_saved = global_step
            if args.limit_steps and global_step >= args.limit_steps:
                done = True
                break
        skip = 0
        dt = time.perf_counter() - t0
        if hvd.process_rank() == 0 and loss is not None:
            print(f"epoch {epoch}: loss={float(loss):.4f} "
                  f"{seen / dt:.1f} img/s ({seen / dt / hvd.size():.1f} "
                  f"img/s/chip)")
    if mgr and last_saved != global_step:
        mgr.save(global_step, {
            "params": params, "batch_stats": batch_stats,
            "opt_state": opt_state, "step": global_step, "epoch": epoch,
            "opt_config": opt_config,
        }, asynchronous=True, force=True)
    if mgr:
        mgr.wait_until_finished()
    if hvd.process_rank() == 0:
        tail = f", final loss {float(loss):.4f}" if loss is not None else ""
        print(f"done at step {global_step}{tail}")


if __name__ == "__main__":
    main()
