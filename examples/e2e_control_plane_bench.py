#!/usr/bin/env python
"""End-to-end control-plane benchmark: native async core vs in-jit allreduce.

The reference's design premise is that gradient negotiation + launch runs on
a background thread, off the training critical path
(``common/ops/gpu_operations.h:49-62``). This benchmark proves the TPU-native
analog end to end on a REAL >=100-tensor model (ResNet-50, ~161 grad leaves):

- **in-jit path**: ``make_shardmap_train_step`` — grads allreduced by
  ``lax.psum`` inside one compiled step (XLA fuses/overlaps; the ceiling).
- **native-core path**: grads computed per-shard in one jitted program,
  every leaf enqueued by NAME through the C++ core (negotiation, response
  cache, fusion bin-packing on the background cycle thread), grouped XLA
  launches on completion, then a jitted apply step.

Reports steps/s for both, the ratio, and a cycle-cost breakdown: Python time
spent inside ``_on_execute`` (parse → group → dispatch) per step, measured on
the core's own thread. ``--autotune`` additionally runs the GP autotuner
under this full load and reports the tuned (cycle, fusion, cache) triple vs
defaults (reference observability: ``common/parameter_manager.cc:44-81``).

Run (8-device virtual CPU mesh):
    python examples/e2e_control_plane_bench.py [--steps 20] [--autotune]

Emits one JSON line per configuration.
"""

import argparse
import json
import os
import sys
import time

# self-sufficient from any cwd (`python examples/e2e_control_plane_bench.py`
# puts examples/ on sys.path[0], not the repo root)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.run.env_util import install_sigterm_exit

install_sigterm_exit()  # watchdog SIGTERM -> clean device teardown


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-per-dev", type=int, default=2)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--filters", type=int, default=16,
                   help="ResNet-50 base width (16 keeps CPU compute small "
                        "so control-plane cost is visible, not masked)")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--cycle-time-ms", type=float, default=1.0)
    p.add_argument("--platform", default="cpu",
                   help="cpu (default: virtual mesh) or leave unset for TPU")
    args = p.parse_args()

    if args.platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
    if args.autotune:
        os.environ.setdefault("HOROVOD_AUTOTUNE", "1")
        os.environ.setdefault("HOROVOD_AUTOTUNE_LOG", "/tmp/autotune_e2e.csv")
        os.environ.setdefault("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
        os.environ.setdefault("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "3")

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.core import NativeCore, REQUEST_ALLREDUCE
    from horovod_tpu.models import ResNet50
    from horovod_tpu.ops import collective
    from horovod_tpu.training import init_model, make_shardmap_train_step, \
        replicate, shard_batch

    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    ax = hvd.basics.data_axis()

    model = ResNet50(num_classes=10, num_filters=args.filters,
                     dtype=jnp.float32)
    tx = optax.sgd(0.05)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3), jnp.float32)
    params, batch_stats = init_model(model, rng, sample)

    batch = n * args.batch_per_dev
    rs = np.random.RandomState(0)
    images_np = rs.rand(batch, args.image_size, args.image_size, 3).astype(
        np.float32)
    labels_np = rs.randint(0, 10, batch)

    n_leaves = len(jax.tree_util.tree_leaves(params))

    def fence(x):
        # device->host read per step: block_until_ready alone does not
        # reliably fence an async dispatch chain (verify-skill gotcha)
        return float(np.asarray(x).ravel()[0])

    # ---------------- path A: in-jit ----------------
    step_jit = make_shardmap_train_step(model, tx, donate=False)
    pA = replicate(params)
    sA = replicate(batch_stats)
    oA = replicate(tx.init(params))
    xA, yA = shard_batch(images_np), shard_batch(labels_np)
    pA, sA, oA, loss = step_jit(pA, sA, oA, xA, yA)  # compile
    fence(loss)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        pA, sA, oA, loss = step_jit(pA, sA, oA, xA, yA)
        fence(loss)
    injit_sps = args.steps / (time.perf_counter() - t0)

    # ---------------- path B: native core ----------------
    # grads per-shard (stacked [n, ...] per leaf), NO reduction in-jit: the
    # exchange goes through the core exactly like the reference's hook path
    def shard_grads(params, batch_stats, images, labels):
        def loss_and_stats(p):
            variables = {"params": p, "batch_stats": batch_stats}
            logits, updates = model.apply(
                variables, images, train=True, mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(labels, 10)
            loss = -jnp.mean(
                jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1))
            return loss, updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_and_stats, has_aux=True)(params)
        # stack per-device values on a new leading dim
        grads = jax.tree_util.tree_map(lambda g: g[None], grads)
        return grads, new_stats, loss

    rep, sh = P(), P(ax)
    grads_fn = jax.jit(collective._smap(
        shard_grads, mesh, (rep, rep, sh, sh),
        (P(ax), rep, rep),
    ))

    def apply_fn(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    apply_jit = jax.jit(apply_fn)

    # instrument the core's Python data plane (runs on the cycle thread).
    # Patch the CLASS before construction: __init__ registers the bound
    # callback with ctypes, so a later instance patch never fires.
    exec_time = [0.0]
    orig_on_execute = NativeCore._on_execute

    def timed_on_execute(self, *a):
        t = time.perf_counter()
        try:
            return orig_on_execute(self, *a)
        finally:
            exec_time[0] += time.perf_counter() - t

    NativeCore._on_execute = timed_on_execute

    core = NativeCore(rank=0, size=1)
    core.cycle_time_ms = args.cycle_time_ms

    pB = replicate(params)
    sB = replicate(batch_stats)
    oB = replicate(tx.init(params))

    leaves0, treedef = jax.tree_util.tree_flatten(params)
    names = [f"grad_{i}" for i in range(len(leaves0))]

    phase = {"grad": 0.0, "enqueue": 0.0, "wait": 0.0, "apply": 0.0}

    def core_step(pB, sB, oB):
        t0 = time.perf_counter()
        grads, sB, loss = grads_fn(pB, sB, xA, yA)
        t1 = time.perf_counter()
        gl, _ = jax.tree_util.tree_flatten(grads)
        hs = [core.enqueue(nm, g, REQUEST_ALLREDUCE, op=1, axis=ax)
              for nm, g in zip(names, gl)]
        t2 = time.perf_counter()
        red = [h.wait(timeout=120) for h in hs]
        t3 = time.perf_counter()
        grads_red = jax.tree_util.tree_unflatten(treedef, red)
        pB, oB = apply_jit(pB, oB, grads_red)
        if jax.default_backend() == "cpu":
            # single-core hosts: an async apply program overlapping the
            # cycle thread's next collective launch can starve XLA:CPU's
            # in-process rendezvous (fixed 20s/40s timeouts) — fence here.
            # TPU streams order per-device work; no fence needed there.
            jax.block_until_ready(pB)
        t4 = time.perf_counter()
        phase["grad"] += t1 - t0
        phase["enqueue"] += t2 - t1
        phase["wait"] += t3 - t2
        phase["apply"] += t4 - t3
        return pB, sB, oB, loss

    warmup = 5 if not args.autotune else 60  # autotune needs samples to tune
    for _ in range(warmup):
        pB, sB, oB, loss = core_step(pB, sB, oB)
    fence(loss)
    exec_time[0] = 0.0
    for k in phase:
        phase[k] = 0.0
    t0 = time.perf_counter()
    for _ in range(args.steps):
        pB, sB, oB, loss = core_step(pB, sB, oB)
        fence(loss)
    dt = time.perf_counter() - t0
    core_sps = args.steps / dt

    ratio = round(core_sps / injit_sps, 3)
    out = {
        "metric": "control_plane_e2e",
        # primary value: async-named-path throughput as a fraction of the
        # in-jit ceiling (1.0 = control plane fully off the critical path) —
        # keyed as "value" so the TPU window watcher can treat this like any
        # other ladder rung; "core_vs_injit" kept as the documented alias
        "value": ratio,
        "unit": "core_vs_injit_ratio",
        "platform": jax.devices()[0].platform,
        "model": "resnet50",
        "n_grad_tensors": n_leaves,
        "devices": n,
        "injit_steps_per_sec": round(injit_sps, 3),
        "core_steps_per_sec": round(core_sps, 3),
        "core_vs_injit": ratio,
        "on_execute_ms_per_step": round(exec_time[0] / args.steps * 1e3, 2),
        "step_ms": round(dt / args.steps * 1e3, 2),
        "phase_ms": {k: round(v / args.steps * 1e3, 2)
                     for k, v in phase.items()},
        "cache_hot": True,
    }
    if args.autotune:
        out["autotune"] = {
            "active": core.autotune_active(),
            "samples": core.autotune_samples(),
            "best_score": core.autotune_best_score(),
            "tuned_cycle_time_ms": core.cycle_time_ms,
            "tuned_fusion_threshold": core.fusion_threshold,
            "tuned_cache_enabled": core.cache_enabled(),
            "log": os.environ.get("HOROVOD_AUTOTUNE_LOG"),
        }
    print(json.dumps(out), flush=True)
    core.shutdown()
    hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
