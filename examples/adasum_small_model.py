#!/usr/bin/env python
"""Adasum demo — analog of reference ``examples/adasum_small_model.py``:
train the same tiny model with op=Average vs op=Adasum and print both loss
curves. Adasum's scaled pairwise combine
(``a' = (1 - dot/2|a|^2) a + (1 - dot/2|b|^2) b``, reference
``adasum.h:194-398``) adapts the effective step to gradient agreement, so it
tolerates larger learning rates than plain averaging."""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MLP
from horovod_tpu.training import make_shardmap_train_step, replicate, shard_batch


def run(op, lr, steps=30):
    model = MLP(features=(32, 10))
    tx = optax.sgd(lr)
    rng = np.random.RandomState(0)
    x = rng.rand(64 * hvd.size(), 16).astype(np.float32)
    teacher = rng.randn(16, 10).astype(np.float32)
    y = (x @ teacher).argmax(1)

    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)), train=True)
    params = replicate(variables["params"])
    opt_state = replicate(tx.init(params))
    step = make_shardmap_train_step(model, tx, reduce_op=op)
    bx, by = shard_batch(x), shard_batch(np.asarray(y))
    losses = []
    batch_stats = {}
    for _ in range(steps):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, bx, by
        )
        losses.append(float(loss))
    return losses


def steps_to_threshold(losses, threshold):
    """First step index (1-based) at which the loss reaches ``threshold``;
    None if it never does."""
    for i, loss in enumerate(losses):
        if loss <= threshold:
            return i + 1
    return None


def compare_steps_to_threshold(base_lr=0.5, adasum_lr_scale=2.5,
                               threshold=0.45, steps=100):
    """Quantify the reference's Adasum claim (docs/adasum_user_guide.rst
    case study): with Adasum the LR scales by ~2-2.5 (not xN), and the run
    reaches the loss threshold in fewer steps than plain averaging.
    Returns (avg_steps, adasum_steps, curves)."""
    avg = run(hvd.Average, base_lr, steps)
    ada = run(hvd.Adasum, base_lr * adasum_lr_scale, steps)
    return (
        steps_to_threshold(avg, threshold),
        steps_to_threshold(ada, threshold),
        {"average": avg, "adasum": ada},
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--threshold", type=float, default=0.45)
    p.add_argument("--adasum-lr-scale", type=float, default=2.5)
    args = p.parse_args()
    hvd.init()
    # three runs serve both outputs: the same-lr loss table (strategy
    # comparison) and the reference's quantitative claim — Adasum at the
    # SCALED lr reaches the threshold in fewer steps than averaging at the
    # base lr (docs/adasum_user_guide.rst case study)
    steps = max(args.steps, 100)
    avg = run(hvd.Average, args.lr, steps)
    ada = run(hvd.Adasum, args.lr, args.steps)
    ada_scaled = run(hvd.Adasum, args.lr * args.adasum_lr_scale, steps)
    if hvd.rank() == 0:
        print(f"{'step':>4} {'average':>10} {'adasum':>10}")
        for i in range(0, args.steps, max(1, args.steps // 10)):
            print(f"{i:>4} {avg[i]:>10.4f} {ada[i]:>10.4f}")
        print(f"final: average={avg[args.steps - 1]:.4f} "
              f"adasum={ada[-1]:.4f}")
        avg_n = steps_to_threshold(avg, args.threshold)
        ada_n = steps_to_threshold(ada_scaled, args.threshold)
        ratio = (ada_n / avg_n) if (avg_n and ada_n) else None
        print(
            f"steps to loss<={args.threshold}: average(lr={args.lr})={avg_n} "
            f"adasum(lr={args.lr * args.adasum_lr_scale})={ada_n} "
            f"ratio={ratio if ratio is None else round(ratio, 3)}"
        )


if __name__ == "__main__":
    main()
