#!/usr/bin/env python
"""Adasum demo — analog of reference ``examples/adasum_small_model.py``:
train the same tiny model with op=Average vs op=Adasum and print both loss
curves. Adasum's scaled pairwise combine
(``a' = (1 - dot/2|a|^2) a + (1 - dot/2|b|^2) b``, reference
``adasum.h:194-398``) adapts the effective step to gradient agreement, so it
tolerates larger learning rates than plain averaging."""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models import MLP
from horovod_tpu.training import make_shardmap_train_step, replicate, shard_batch


def run(op, lr, steps=30):
    model = MLP(features=(32, 10))
    tx = optax.sgd(lr)
    rng = np.random.RandomState(0)
    x = rng.rand(64 * hvd.size(), 16).astype(np.float32)
    teacher = rng.randn(16, 10).astype(np.float32)
    y = (x @ teacher).argmax(1)

    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)), train=True)
    params = replicate(variables["params"])
    opt_state = replicate(tx.init(params))
    step = make_shardmap_train_step(model, tx, reduce_op=op)
    bx, by = shard_batch(x), shard_batch(np.asarray(y))
    losses = []
    batch_stats = {}
    for _ in range(steps):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, bx, by
        )
        losses.append(float(loss))
    return losses


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--steps", type=int, default=30)
    args = p.parse_args()
    hvd.init()
    avg = run(hvd.Average, args.lr, args.steps)
    ada = run(hvd.Adasum, args.lr, args.steps)
    if hvd.rank() == 0:
        print(f"{'step':>4} {'average':>10} {'adasum':>10}")
        for i in range(0, args.steps, max(1, args.steps // 10)):
            print(f"{i:>4} {avg[i]:>10.4f} {ada[i]:>10.4f}")
        print(f"final: average={avg[-1]:.4f} adasum={ada[-1]:.4f}")


if __name__ == "__main__":
    main()
