"""Long-context transformer training with DP x SP ring attention.

TPU-native capability beyond the reference (Horovod 0.19.2 is batch-axis
only): the sequence axis is sharded over the mesh, attention runs as a ring
(`horovod_tpu.parallel.ring_attention`), and gradients combine over both the
data and sequence axes. Run on an 8-chip host:

    python examples/transformer_long_context.py --seq-len 32768 --dp 2

(For CPU experimentation: XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu with small --seq-len.)
"""

import argparse
import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import TransformerLM
from horovod_tpu.parallel import SEQUENCE_AXIS, ring_attention
from horovod_tpu.training import make_sp_train_step, replicate


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=2, help="global batch")
    p.add_argument("--dp", type=int, default=1, help="data-parallel degree")
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=8192)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()

    n = len(jax.devices())
    sp = n // args.dp
    hvd.init(axes={"data": args.dp, SEQUENCE_AXIS: sp})
    print(f"mesh: data={args.dp} seq={sp} ({n} devices), "
          f"context {args.seq_len} tokens")

    kw = dict(vocab=args.vocab, dim=args.dim, depth=args.depth,
              heads=args.heads, max_len=args.seq_len)
    model = TransformerLM(
        attention_fn=functools.partial(ring_attention,
                                       axis_name=SEQUENCE_AXIS),
        **kw,
    )
    tx = optax.adamw(3e-4)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, args.vocab, (args.batch, args.seq_len)).astype(
        np.int32)
    targets = np.roll(tokens, -1, axis=1)

    init_tokens = jnp.asarray(tokens[:1, : max(args.seq_len // sp, 8)])
    params = TransformerLM(**kw).init(
        jax.random.PRNGKey(0), init_tokens)["params"]
    params = replicate(params)
    opt_state = replicate(tx.init(params))

    sh = NamedSharding(hvd.mesh(), P("data", SEQUENCE_AXIS))
    tokens = jax.device_put(jnp.asarray(tokens), sh)
    targets = jax.device_put(jnp.asarray(targets), sh)

    step = make_sp_train_step(model, tx, seq_axis=SEQUENCE_AXIS)
    params, opt_state, loss = step(params, opt_state, tokens, targets)
    print(f"compiled; first loss {float(loss):.4f}")

    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        print(f"step {i}: loss {float(loss):.4f}")
    dt = time.perf_counter() - t0
    tok_s = args.batch * args.seq_len * args.steps / dt
    print(f"{tok_s:,.0f} tokens/s over the mesh")


if __name__ == "__main__":
    main()
