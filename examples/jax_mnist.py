#!/usr/bin/env python
"""MNIST-style training with the JAX frontend — the rebuild's analog of
reference ``examples/tensorflow2_mnist.py``: init → shard data → broadcast
initial state → DistributedOptimizer → rank-0 checkpointing.

Runs on synthetic MNIST-shaped data by default (no dataset download in the
sandbox); pass ``--data-dir`` with an ``mnist.npz`` to use the real digits.

Launch on one host (8-chip mesh in one process):

    python examples/jax_mnist.py

or multi-process via the launcher:

    python -m horovod_tpu.run -np 2 -- python examples/jax_mnist.py
"""

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import checkpoint as ckpt
from horovod_tpu.data import ShardedLoader
from horovod_tpu.models import MnistCNN
from horovod_tpu.training import init_model, make_jit_train_step, replicate


def load_data(data_dir):
    if data_dir and os.path.exists(os.path.join(data_dir, "mnist.npz")):
        d = np.load(os.path.join(data_dir, "mnist.npz"))
        return d["x_train"].astype(np.float32) / 255.0, d["y_train"]
    # synthetic but learnable: images whose class is a linear teacher's argmax
    rng = np.random.RandomState(0)
    x = rng.rand(4096, 28, 28, 1).astype(np.float32)
    teacher = rng.randn(28 * 28, 10).astype(np.float32)
    y = (x.reshape(len(x), -1) @ teacher).argmax(1)
    return x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64, help="per-chip")
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--checkpoint-dir", default="./checkpoints")
    p.add_argument(
        "--limit-steps", type=int, default=0,
        help="cap steps per epoch (0 = full epoch); smoke tests use this",
    )
    args = p.parse_args()

    hvd.init()
    x, y = load_data(args.data_dir)
    if x.ndim == 3:
        x = x[..., None]

    model = MnistCNN()
    # Horovod LR scaling: scale by number of workers (reference
    # examples/tensorflow2_mnist.py: lr * hvd.size())
    tx = hvd.DistributedOptimizer(optax.adam(args.lr * hvd.size()))
    params, batch_stats = init_model(model, jax.random.PRNGKey(0), x[:1])
    params, batch_stats = replicate(params), replicate(batch_stats)
    opt_state = replicate(tx.init(params))

    # all ranks start from rank 0's weights (reference
    # BroadcastGlobalVariablesHook / broadcast_variables)
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)

    step_fn = make_jit_train_step(model, tx)
    global_batch = args.batch_size * hvd.size()
    # sharded + device-prefetching input pipeline: batch i+1's host->HBM
    # copy overlaps batch i's compute
    loader = ShardedLoader((x, y), global_batch, seed=0, prefetch=2)
    steps_per_epoch = len(loader)
    if args.limit_steps:
        steps_per_epoch = min(steps_per_epoch, args.limit_steps)

    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        for i, (bx, by) in enumerate(loader):
            if i >= steps_per_epoch:
                break
            params, batch_stats, opt_state, loss = step_fn(
                params, batch_stats, opt_state, bx, by
            )
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={float(loss):.4f}")
        # ckpt.save is rank-0-write internally; call it on every rank
        # (it fences so no rank races ahead of the writer)
        ckpt.save(
            args.checkpoint_dir, epoch,
            {"params": params, "opt_state": opt_state}, force=True,
        )


if __name__ == "__main__":
    main()
